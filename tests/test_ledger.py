"""Flight recorder: ledger round-trip, capture wiring, diffing, rotation,
the runs CLI, and the bench regression gate (ARCHITECTURE.md §10)."""

import json
import os
import sys

import pytest

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.telemetry import ledger
from open_simulator_tpu.testing.builders import make_fake_node, make_fake_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_regress  # noqa: E402


@pytest.fixture
def led(tmp_path, monkeypatch):
    """A fresh process-wide ledger rooted in tmp_path; reset afterwards so
    other tests run with recording off."""
    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(str(tmp_path))
    yield ledger.default_ledger()
    ledger.configure(None)


def _small_cluster():
    cluster = ClusterResources()
    cluster.nodes = [make_fake_node(f"n{i}") for i in range(3)]
    app = ClusterResources()
    app.pods = [make_fake_pod(f"p{i}") for i in range(4)]
    return cluster, [AppResource(name="a", resources=app)]


def _record(run_id="r0", ts=1000.0, surface="apply", digest="d0",
            engine="e0", workload="w0", value=None, shape=None,
            phases=None):
    rec = {
        "schema": 1, "run_id": run_id, "ts": ts, "surface": surface,
        "wall_s": 1.0,
        "fingerprint": {"engine": engine, "bucket": [4, 4],
                        "workload": workload},
        "phases": phases or {"encode": 0.01, "schedule": 0.5,
                             "decode": 0.002},
        "metrics": {}, "env": {},
        "result": {"placed": 4, "unplaced": 0, "digest": digest},
        "tags": {},
    }
    if value is not None:
        rec["surface"] = "bench"
        rec["tags"] = {"shape": shape or "8n_x16p_x4s", "value": value,
                       "preset": "demo"}
    return rec


# ---- storage round-trip --------------------------------------------------


def test_append_list_find_round_trip(led):
    led.append(_record("aaa111", ts=1.0))
    led.append(_record("bbb222", ts=2.0, surface="chaos"))
    recs = led.records()
    assert [r["run_id"] for r in recs] == ["aaa111", "bbb222"]
    assert [r["run_id"] for r in led.records(surface="chaos")] == ["bbb222"]
    assert led.find("aaa")["run_id"] == "aaa111"
    assert led.find("last")["run_id"] == "bbb222"
    assert led.find("prev")["run_id"] == "aaa111"
    with pytest.raises(ledger.LedgerError):
        led.find("zzz")
    # ambiguous prefix
    led.append(_record("aaa999", ts=3.0))
    with pytest.raises(ledger.LedgerError):
        led.find("aaa")


def test_corrupt_lines_are_skipped(led):
    led.append(_record("good01"))
    with open(led.path, "a", encoding="utf-8") as f:
        f.write("{truncated json\n")
    led.append(_record("good02", ts=2000.0))
    assert [r["run_id"] for r in led.records()] == ["good01", "good02"]


def test_rotation_at_size_cap(tmp_path):
    small = ledger.Ledger(str(tmp_path), max_bytes=4096)
    for i in range(40):
        small.append(_record(f"run{i:04d}", ts=float(i)))
    # the cap rotated the file at least once, kept ONE prior generation
    assert os.path.exists(small.path + ".1")
    assert os.path.getsize(small.path) <= 4096
    recs = small.records()
    # newest record always survives; total bounded by ~2 generations
    assert recs[-1]["run_id"] == "run0039"
    assert 0 < len(recs) < 40
    # ids stay ordered and unique across the generation boundary
    ids = [r["run_id"] for r in recs]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)


def test_disabled_ledger_is_null_capture(monkeypatch):
    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(None)
    with ledger.run_capture("apply") as cap:
        assert cap is ledger.NULL_CAPTURE
        cap.tag("k", "v")  # all methods are no-ops


# ---- capture wiring ------------------------------------------------------


def test_simulate_records_and_is_deterministic(led):
    cluster, apps = _small_cluster()
    simulate(cluster, apps)
    cluster, apps = _small_cluster()
    simulate(cluster, apps)
    recs = led.records(surface="simulate")
    assert len(recs) == 2
    a, b = recs
    # identical inputs: identical fingerprints AND identical digests
    assert a["fingerprint"] == b["fingerprint"]
    assert a["result"]["digest"] == b["result"]["digest"]
    assert a["result"]["placed"] == 4 and a["result"]["unplaced"] == 0
    assert a["run_id"] != b["run_id"]
    # the span harvest captured the pipeline phases
    for phase in ("encode", "transfer", "schedule", "decode"):
        assert phase in a["phases"], a["phases"]
    assert a["env"].get("backend")


def test_nested_captures_yield_one_record(led):
    """An outer capture claims the run: the simulate() inside must not
    write a second record (one record per logical run)."""
    cluster, apps = _small_cluster()
    with ledger.run_capture("apply") as cap:
        result = simulate(cluster, apps)
        cap.set_result(result)
    recs = led.records()
    assert [r["surface"] for r in recs] == ["apply"]


def test_surface_override_names_the_entry_point(led):
    cluster, apps = _small_cluster()
    with ledger.surface_override("server:/api/deploy-apps"):
        simulate(cluster, apps)
    assert led.records()[-1]["surface"] == "server:/api/deploy-apps"


def test_failed_run_writes_no_record(led):
    cluster, apps = _small_cluster()
    cluster.nodes[0].allocatable["cpu"] = -5  # admission rejects
    with pytest.raises(Exception):
        simulate(cluster, apps)
    assert led.records() == []


def test_sweep_records_both_modes(led):
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel.sweep import capacity_bisect, capacity_sweep
    from open_simulator_tpu.testing.synthetic import synthetic_snapshot

    snap = synthetic_snapshot(n_nodes=4, n_pods=8, max_new=4)
    cfg = make_config(snap)
    capacity_bisect(snap, cfg, 4)
    capacity_sweep(snap, cfg, list(range(5)))
    recs = led.records(surface="sweep")
    assert len(recs) == 2
    for rec in recs:
        assert rec["fingerprint"]["workload"]
        assert rec["result"]["digest"]
        assert "best_count" in rec["tags"]
    # both modes answered the same question about the same workload
    assert (recs[0]["fingerprint"]["workload"]
            == recs[1]["fingerprint"]["workload"])


def test_chaos_records_one_run(led):
    from open_simulator_tpu.resilience.chaos import ChaosPlan, FaultEvent, run_chaos

    cluster, apps = _small_cluster()
    plan = ChaosPlan(events=[FaultEvent("kill_node", "n0")])
    report = run_chaos(cluster, plan, apps)
    recs = led.records()
    assert [r["surface"] for r in recs] == ["chaos"]
    assert recs[0]["result"]["digest"] == ledger.report_digest(report)["digest"]
    assert recs[0]["tags"]["events"] == 1


def test_bench_records_shape_and_value(led):
    sys.path.insert(0, REPO)
    import bench

    snap = bench.build(4, 8, 2)
    bench.run_batched(snap, 2, preset="demo")
    [rec] = led.records(surface="bench")
    assert rec["tags"]["preset"] == "demo"
    assert rec["tags"]["shape"] == bench.shape_label(4, 8, 2)
    assert rec["tags"]["value"] > 0 and rec["tags"]["seconds"] > 0
    assert rec["result"]["digest"] and rec["fingerprint"]["engine"]


def test_compile_cache_metric_delta_flips_to_hit(led):
    """The metric-delta harvest: a repeat run in the same bucket must
    record a cache HIT and no miss (the compile-once contract, now
    visible run-over-run instead of process-locally)."""
    cluster, apps = _small_cluster()
    simulate(cluster, apps)
    cluster, apps = _small_cluster()
    simulate(cluster, apps)
    a, b = led.records(surface="simulate")
    key_hit = "simon_compile_cache_total{event=hit,fn=schedule_pods}"
    key_miss = "simon_compile_cache_total{event=miss,fn=schedule_pods}"
    assert b["metrics"].get(key_hit, 0) >= 1
    assert key_miss not in b["metrics"]
    # run 1 either missed (cold process) or hit (suite already warmed the
    # jit cache) — but it cannot have done neither
    assert (key_hit in a["metrics"]) or (key_miss in a["metrics"])


# ---- fingerprints --------------------------------------------------------


def test_fingerprint_tracks_config_and_workload():
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.testing.synthetic import synthetic_snapshot

    snap = synthetic_snapshot(n_nodes=4, n_pods=8, max_new=0)
    cfg = make_config(snap)
    fp1 = ledger.config_fingerprint(cfg, snapshot=snap)
    fp2 = ledger.config_fingerprint(cfg, snapshot=snap)
    assert fp1 == fp2
    # a config knob flips the engine hash, not the workload digest
    fp3 = ledger.config_fingerprint(
        cfg._replace(fail_reasons=False), snapshot=snap)
    assert fp3["engine"] != fp1["engine"]
    assert fp3["workload"] == fp1["workload"]
    # a different workload flips the workload digest
    snap2 = synthetic_snapshot(n_nodes=4, n_pods=9, max_new=0)
    fp4 = ledger.config_fingerprint(make_config(snap2), snapshot=snap2)
    assert fp4["workload"] != fp1["workload"]


# ---- diffing -------------------------------------------------------------


def test_diff_identical_runs():
    a = _record("run000000000a", ts=1.0)
    b = _record("run000000000b", ts=2.0)
    d = ledger.diff_records(a, b)
    assert d["fingerprint"]["match"] and not d["fingerprint"]["drift"]
    assert d["result"]["identical"] and not d["result"]["nondeterministic"]
    text = ledger.format_diff(d)
    assert "MATCH" in text and "IDENTICAL" in text
    assert "schedule" in text


def test_diff_flags_nondeterminism_and_drift():
    a = _record("runa", ts=1.0, digest="d0")
    # same fingerprint, different digest -> nondeterminism
    b = _record("runb", ts=2.0, digest="d1")
    d = ledger.diff_records(a, b)
    assert d["result"]["nondeterministic"]
    assert "NONDETERMINISM" in ledger.format_diff(d)
    # drifted engine config explains a digest change: NOT nondeterminism
    c = _record("runc", ts=3.0, digest="d1", engine="e9")
    d2 = ledger.diff_records(a, c)
    assert d2["fingerprint"]["drift"] == ["engine"]
    assert not d2["result"]["nondeterministic"]
    text = ledger.format_diff(d2)
    assert "DRIFT" in text and "engine config changed" in text


def test_diff_phase_rows_percentages():
    a = _record("runa", phases={"encode": 0.10, "schedule": 1.0})
    b = _record("runb", ts=2000.0,
                phases={"encode": 0.05, "schedule": 2.0, "compile": 1.5})
    rows = {r["phase"]: r for r in ledger.diff_records(a, b)["phases"]}
    assert rows["encode"]["pct"] == -50.0
    assert rows["schedule"]["pct"] == 100.0
    assert rows["compile"]["a_s"] is None  # present only in run b


# ---- runs CLI ------------------------------------------------------------


def test_runs_cli_list_show_diff(led, capsys):
    from open_simulator_tpu.cli.main import main

    led.append(_record("aaa111", ts=1.0))
    led.append(_record("bbb222", ts=2.0))
    root = led.root

    assert main(["runs", "--ledger-dir", root, "list"]) == 0
    out = capsys.readouterr().out
    assert "aaa111" in out and "bbb222" in out

    assert main(["runs", "--ledger-dir", root, "list", "--json",
                 "--surface", "apply", "-n", "1"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert [r["run_id"] for r in rows] == ["bbb222"]

    assert main(["runs", "--ledger-dir", root, "show", "aaa"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["run_id"] == "aaa111"

    assert main(["runs", "--ledger-dir", root, "diff", "prev", "last"]) == 0
    out = capsys.readouterr().out
    assert "IDENTICAL" in out and "phases" in out

    assert main(["runs", "--ledger-dir", root, "show", "zzz"]) == 1
    assert "no run id matches" in capsys.readouterr().err


def test_runs_cli_without_ledger_errors(monkeypatch, capsys):
    from open_simulator_tpu.cli.main import main

    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(None)
    assert main(["runs", "list"]) == 1
    assert "no run ledger configured" in capsys.readouterr().err


def test_apply_cli_two_runs_identical_digests(led, capsys):
    """The acceptance scenario: two consecutive `simon-tpu apply` runs of
    the demo config against one ledger -> two RunRecords with identical
    result digests and matching config fingerprints, and `runs diff`
    renders per-phase deltas without error."""
    from open_simulator_tpu.cli.main import main

    cfg_path = os.path.join(REPO, "examples/config.yaml")
    for _ in range(2):
        assert main(["apply", "-f", cfg_path, "--max-new-nodes", "4",
                     "--output-file", os.devnull]) == 0
    capsys.readouterr()
    a, b = led.records(surface="apply")
    assert a["result"]["digest"] == b["result"]["digest"]
    assert a["fingerprint"] == b["fingerprint"]
    assert a["tags"]["sweep_mode"] == "bisect"
    assert main(["runs", "--ledger-dir", led.root, "diff", "prev", "last"]) == 0
    out = capsys.readouterr().out
    assert "IDENTICAL" in out and "sweep" in out


# ---- bench regression gate ----------------------------------------------


def test_bench_regress_no_op_paths(led, capsys):
    # empty ledger -> clean no-op
    assert bench_regress.main(["--ledger-dir", led.root]) == 0
    assert "nothing to gate" in capsys.readouterr().out
    # one record per shape -> still a no-op (no history)
    led.append(_record("r1", ts=1.0, value=100.0))
    assert bench_regress.main(["--ledger-dir", led.root]) == 0
    assert "no history" in capsys.readouterr().out


def test_bench_regress_passes_within_threshold(led, capsys):
    for i, v in enumerate([100.0, 104.0, 96.0, 98.0]):
        led.append(_record(f"r{i}", ts=float(i), value=v))
    assert bench_regress.main(["--ledger-dir", led.root]) == 0
    assert "OK" in capsys.readouterr().out


def test_bench_regress_fails_on_slowed_record(led, capsys):
    for i, v in enumerate([100.0, 102.0, 98.0]):
        led.append(_record(f"r{i}", ts=float(i), value=v))
    # synthetically slowed newest record: 40% below the trailing median
    led.append(_record("slow", ts=99.0, value=60.0))
    assert bench_regress.main(["--ledger-dir", led.root]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "FAILED" in out
    # a tolerant threshold lets the same ledger pass
    assert bench_regress.main(
        ["--ledger-dir", led.root, "--threshold", "0.5"]) == 0


def test_bench_regress_gates_shapes_independently(led, capsys):
    for i, v in enumerate([100.0, 100.0]):
        led.append(_record(f"a{i}", ts=float(i), value=v, shape="s_a"))
    led.append(_record("b0", ts=10.0, value=50.0, shape="s_b"))
    led.append(_record("b1", ts=11.0, value=10.0, shape="s_b"))  # -80%
    assert bench_regress.main(["--ledger-dir", led.root]) == 1
    out = capsys.readouterr().out
    assert "s_b" in out and "FAILED" in out and "s_a" in out


def test_bench_regress_without_any_ledger(monkeypatch, capsys):
    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(None)
    assert bench_regress.main([]) == 0
    assert "no ledger configured" in capsys.readouterr().out
