"""Causal request tracing: trace context, the black-box flight
recorder, and the /api/trace surfaces (telemetry/context.py,
ARCHITECTURE.md section 20).

Covers:
* trace-id minting/validation and contextvar scope semantics (nesting,
  tuple normalization for coalesced groups);
* the bounded black-box ring: overflow drops oldest, dropped counting;
* the span recorder's overflow accounting (simon_spans_dropped_total
  keeps the NEWEST window);
* HTTP round-trip: X-Simon-Trace-Id in -> echoed back -> GET
  /api/trace/<id> reconstructs the causal timeline (queue admission,
  dequeue wait, coalesced launch, final status);
* per-request span-window marks (the old single server._trace_mark slot
  was clobbered by concurrent workers);
* deterministic fault injection on a coalesced group: the poisoned
  member's timeline carries its OWN structured error while the sibling
  shows the shared launch + rungs walked; an injected OOM's timeline
  records the cache_drop rung with attempt numbers.
"""

import json
import textwrap
import threading
import urllib.error
import urllib.request

import pytest

from http.server import ThreadingHTTPServer

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.server.rest import SimulationServer, _make_handler
from open_simulator_tpu.telemetry import context

CLUSTER_YAML = textwrap.dedent("""
    apiVersion: v1
    kind: Node
    metadata: {name: t0}
    status:
      allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
    ---
    apiVersion: v1
    kind: Node
    metadata: {name: t1}
    status:
      allocatable: {cpu: "4", memory: 8Gi, pods: "110"}
    ---
    apiVersion: apps/v1
    kind: Deployment
    metadata: {name: app, namespace: default}
    spec:
      replicas: 2
      selector: {matchLabels: {app: a}}
      template:
        metadata: {labels: {app: a}}
        spec:
          containers:
            - name: c
              resources: {requests: {cpu: "1", memory: 1Gi}}
""")


# ---- trace context (pure host machinery) ---------------------------------


def test_ensure_trace_header_validation():
    assert context.ensure_trace("req-1.a:b_c") == "req-1.a:b_c"
    assert context.ensure_trace("  padded-ok  ") == "padded-ok"
    # invalid ids (charset, length, empty) get a minted id, never an error
    for bad in (None, "", "has space", "x" * 129, "semi;colon", "a\nb"):
        minted = context.ensure_trace(bad)
        assert minted != bad
        assert context.valid_trace_id(minted)
    assert context.valid_trace_id(context.new_trace_id())


def test_trace_scope_nesting_and_tuple_normalization():
    assert context.current_trace() is None
    assert context.current_traces() == ()
    with context.trace_scope("outer") as primary:
        assert primary == "outer"
        assert context.current_traces() == ("outer",)
        # a coalesced-group tuple SHADOWS the worker's ambient scope
        with context.trace_scope(["a", "b", "a", "b", "c"]) as p2:
            assert p2 == "a"  # primary = first member
            assert context.current_traces() == ("a", "b", "c")  # deduped
        assert context.current_traces() == ("outer",)  # restored
        with context.trace_scope(None):
            assert context.current_trace() is None  # explicit untraced
    assert context.current_trace() is None


def test_blackbox_ring_bounded_drops_oldest():
    box = context.BlackBox(maxlen=4)
    for i in range(7):
        box.record("enqueue", trace=f"t{i}", seq=i)
    st = box.stats()
    assert st["events"] == 4 and st["recorded"] == 7 and st["dropped"] == 3
    # oldest gone, newest retained (the crash narrative is at the end)
    assert box.events_for("t0") == []
    assert box.events_for("t6")[0]["seq"] == 6
    assert box.latest(kind="enqueue")["seq"] == 6
    assert box.latest(kind="nope") is None


def test_timeline_unknown_trace_is_none():
    assert context.timeline("never-seen-" + context.new_trace_id()) is None


def test_blackbox_membership_match_group_tuple():
    box = context.BlackBox(maxlen=16)
    with context.trace_scope(("m1", "m2")):
        box.record("launch", members=2)
    # one physical launch belongs to BOTH logical requests
    assert len(box.events_for("m1")) == 1
    assert len(box.events_for("m2")) == 1
    assert box.events_for("m1")[0]["traces"] == ("m1", "m2")


def test_span_recorder_overflow_counts_and_keeps_newest():
    from open_simulator_tpu.telemetry import registry
    from open_simulator_tpu.telemetry.spans import (
        SPANS_DROPPED_TOTAL,
        SpanRecorder,
    )

    rec = SpanRecorder(maxlen=3)
    before = registry.counter(
        SPANS_DROPPED_TOTAL, "span records evicted").value()
    for i in range(5):
        rec.add(f"phase{i}", t0=float(i), dur=0.001)
    assert rec.dropped == 2
    names = [r.name for r in rec.records()]
    assert names == ["phase2", "phase3", "phase4"]  # newest window kept
    after = registry.counter(SPANS_DROPPED_TOTAL, "").value()
    assert after == before + 2


# ---- HTTP round-trip ------------------------------------------------------


@pytest.fixture(scope="module")
def traced_server():
    srv = SimulationServer(workers=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", srv
    httpd.shutdown()


def _post(url, payload, trace_id=None):
    headers = {"Content-Type": "application/json"}
    if trace_id:
        headers[context.TRACE_HEADER] = trace_id
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=headers)
    with urllib.request.urlopen(req) as resp:
        return (resp.status, resp.headers.get(context.TRACE_HEADER),
                json.loads(resp.read()))


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture(scope="module")
def warm_digest(traced_server):
    """One warm POST: admits the snapshot + compiles the serving
    executable; later tests coalesce probes against its digest."""
    url, _srv = traced_server
    status, _echo, out = _post(url + "/api/simulate",
                               {"cluster": {"yaml": CLUSTER_YAML}},
                               trace_id="warmup-req")
    assert status == 200
    return out["snapshot_digest"]


def test_trace_roundtrip_header_echo_and_timeline(traced_server,
                                                  warm_digest):
    url, _srv = traced_server
    tid = "roundtrip-" + context.new_trace_id()
    status, echo, _out = _post(url + "/api/simulate",
                               {"base": warm_digest}, trace_id=tid)
    assert status == 200
    assert echo == tid  # client-supplied id echoed on the response
    code, tl = _get(url + f"/api/trace/{tid}")
    assert code == 200 and tl["trace_id"] == tid
    kinds = [e["kind"] for e in tl["events"]]
    assert "enqueue" in kinds     # queue admission
    assert "dequeue" in kinds     # worker pickup, with measured wait
    assert "launch" in kinds      # the (possibly coalesced) launch
    assert "response" in kinds    # final status
    s = tl["summary"]
    assert s["status"] == 200 and s["error_code"] is None
    assert s["queue_wait_ms"] is not None and s["launches"] >= 1


def test_trace_minted_id_echoed_when_client_sends_none(traced_server,
                                                       warm_digest):
    url, _srv = traced_server
    status, echo, _out = _post(url + "/api/simulate",
                               {"base": warm_digest})
    assert status == 200
    assert context.valid_trace_id(echo)  # server minted one and said so
    code, tl = _get(url + f"/api/trace/{echo}")
    assert code == 200 and tl["summary"]["status"] == 200


def test_trace_unknown_id_structured_404(traced_server):
    url, _srv = traced_server
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url + "/api/trace/no-such-trace")
    assert ei.value.code == 404
    body = json.loads(ei.value.read())
    assert body["code"] == "E_NO_TRACE" and body["hint"]


def test_span_windows_are_per_request_not_clobbered(traced_server,
                                                    warm_digest):
    """Regression for the racy global last-POST marker: each POST's
    span-window mark rides its own black-box "request" event, so a
    second worker's request can no longer clobber the first's window.
    The bare GET /api/trace keeps its old meaning (the newest window)."""
    url, srv = traced_server
    ta = "win-a-" + context.new_trace_id()
    tb = "win-b-" + context.new_trace_id()
    _post(url + "/api/simulate", {"base": warm_digest}, trace_id=ta)
    _post(url + "/api/simulate", {"base": warm_digest}, trace_id=tb)
    marks = [e for e in context.BLACKBOX.events_for(ta)
             + context.BLACKBOX.events_for(tb)
             if e["kind"] == "request" and "span_mark" in e]
    assert len(marks) == 2  # one retained mark PER request
    assert marks[0]["span_mark"] != marks[1]["span_mark"]
    assert all(m["server_id"] == id(srv) for m in marks)
    # both requests' own timelines survived intact — nothing clobbered
    for tid in (ta, tb):
        _code, tl = _get(url + f"/api/trace/{tid}")
        assert tl["summary"]["launches"] >= 1
    code, trace_doc = _get(url + "/api/trace")
    assert code == 200 and "traceEvents" in trace_doc


def test_debug_executables_and_stats_surfaces(traced_server, warm_digest):
    url, _srv = traced_server
    _code, out = _get(url + "/debug/executables")
    assert out["entries"], "warmed executable missing from /debug/executables"
    assert any(row.get("cost", {}).get("compile_s", 0) > 0
               for row in out["entries"])
    _code, stats = _get(url + "/debug/stats")
    assert "spans_dropped" in stats
    assert stats["blackbox"]["capacity"] > 0
    assert stats["blackbox"]["events"] > 0


# ---- deterministic faults on a coalesced group ----------------------------


def _probe_jobs(srv, digest, traces):
    from open_simulator_tpu.server import serving

    class _FakeJob:
        def __init__(self, payload, trace):
            self.payload = payload
            self.token = None
            self.result = None
            self.trace = trace

    return [_FakeJob(serving.prepare_simulate(srv, {"base": digest}), t)
            for t in traces]


def test_poisoned_member_timeline_vs_sibling(traced_server, warm_digest):
    """One deterministic numeric poison that follows the batch split
    down to ONE member: that member's timeline ends in its own
    structured error; the sibling's shows the shared launch (with the
    poisoned id listed as a coalesced sibling) and no error."""
    from open_simulator_tpu.server import serving

    url, srv = traced_server
    bad_t = "poison-" + context.new_trace_id()
    ok_t = "healthy-" + context.new_trace_id()
    group = _probe_jobs(srv, warm_digest, [bad_t, ok_t])
    with faults.injected("fn=serving_lanes,exc=numeric,times=2"):
        # the worker runs a coalesced group under the member tuple
        # (resilience/lifecycle.py _run_group) — mirrored here
        with context.trace_scope((bad_t, ok_t)):
            serving.execute_group(group)
    outcomes = sorted((j.result[0], j.result[1].get("code"))
                      for j in group)
    assert outcomes == [(200, None), (500, "E_NUMERIC")], outcomes

    _code, bad_tl = _get(url + f"/api/trace/{bad_t}")
    _code, ok_tl = _get(url + f"/api/trace/{ok_t}")
    # the poisoned member owns its structured error...
    assert bad_tl["summary"]["error_code"] == "E_NUMERIC"
    err = [e for e in bad_tl["events"] if e["kind"] == "error"]
    assert err and err[0]["traces"] == [bad_t]  # the member's OWN event
    # ...the sibling answered 200: shared launch recorded, no error
    assert ok_tl["summary"]["error_code"] is None
    assert ok_tl["summary"]["launches"] >= 1
    assert bad_t in ok_tl["summary"]["siblings"]
    # both walked the same degradation ladder (batch_split rung)
    for tl in (bad_tl, ok_tl):
        assert any(r["rung"] == "batch_split"
                   for r in tl["summary"]["rungs"]), tl["summary"]


def test_injected_oom_timeline_records_cache_drop_and_attempts(
        traced_server, warm_digest):
    """A fault-plan OOM on the coalesced launch: the timeline shows the
    cache_drop rung and numbered attempts (initial + post-drop retry)."""
    from open_simulator_tpu.server import serving

    url, srv = traced_server
    tid = "oom-" + context.new_trace_id()
    group = _probe_jobs(srv, warm_digest, [tid])
    with faults.injected("fn=serving_lanes,exc=oom,times=1"):
        with context.trace_scope((tid,)):
            serving.execute_group(group)
    assert group[0].result[0] == 200  # the ladder absorbed the fault
    _code, tl = _get(url + f"/api/trace/{tid}")
    assert any(r["rung"] == "cache_drop" and r["code"] == "E_DEVICE_OOM"
               for r in tl["summary"]["rungs"]), tl["summary"]
    attempts = [e["attempt"] for e in tl["events"]
                if e["kind"] == "attempt"]
    assert 0 in attempts and len(attempts) >= 2  # numbered retries
    assert tl["summary"]["attempts"] >= 2
