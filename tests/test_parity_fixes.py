"""Regression tests for k8s-parity semantics found in review."""

from open_simulator_tpu.k8s.objects import Pod, Taint, Toleration
from open_simulator_tpu.k8s.selectors import node_selector_terms_match, tolerates_taints


def test_toleration_missing_operator_defaults_to_equal():
    # {key, effect} with no operator tolerates only `dedicated=` (empty value),
    # NOT dedicated=gpu — k8s defaults operator to Equal.
    tol = Toleration.from_dict({"key": "dedicated", "effect": "NoSchedule"})
    assert tol.operator == "Equal" and tol.value == ""
    gpu_taint = Taint(key="dedicated", value="gpu", effect="NoSchedule")
    empty_taint = Taint(key="dedicated", value="", effect="NoSchedule")
    assert not tolerates_taints([gpu_taint], [tol])
    assert tolerates_taints([empty_taint], [tol])


def test_init_containers_max_semantics():
    pod = Pod.from_dict({
        "metadata": {"name": "p"},
        "spec": {
            "containers": [{"name": "c", "resources": {"requests": {"cpu": "100m", "memory": "64Mi"}}}],
            "initContainers": [
                {"name": "i1", "resources": {"requests": {"cpu": "4", "memory": "8Gi"}}},
                {"name": "i2", "resources": {"requests": {"cpu": "2"}}},
            ],
        },
    })
    req = pod.requests()
    assert req["cpu"] == 4000      # max(100, 4000, 2000)
    assert req["memory"] == 8192   # max(64, 8192)


def test_empty_node_selector_term_matches_nothing():
    assert not node_selector_terms_match({"zone": "a"}, [{}])
    # but a valid sibling term still matches (OR semantics)
    terms = [{}, {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}]
    assert node_selector_terms_match({"zone": "a"}, terms)


def test_gpu_resource_form_participates_in_fit():
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from tests.conftest import make_node, make_pod

    # node without GPUs; pod requests the gpu-mem *resource* form
    cluster = ClusterResources()
    cluster.nodes = [make_node("cpu-only")]
    app = ClusterResources()
    pod = Pod.from_dict({
        "metadata": {"name": "gpu-pod", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {
            "requests": {"cpu": "100m", "alibabacloud.com/gpu-mem": "8"}}}]},
    })
    app.pods = [pod]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(res.unscheduled_pods) == 1
    assert "Insufficient alibabacloud.com/gpu-mem" in res.unscheduled_pods[0].reason
