"""Regression tests for k8s-parity semantics found in review."""

from open_simulator_tpu.k8s.objects import Pod, Taint, Toleration
from open_simulator_tpu.k8s.selectors import node_selector_terms_match, tolerates_taints


def test_toleration_missing_operator_defaults_to_equal():
    # {key, effect} with no operator tolerates only `dedicated=` (empty value),
    # NOT dedicated=gpu — k8s defaults operator to Equal.
    tol = Toleration.from_dict({"key": "dedicated", "effect": "NoSchedule"})
    assert tol.operator == "Equal" and tol.value == ""
    gpu_taint = Taint(key="dedicated", value="gpu", effect="NoSchedule")
    empty_taint = Taint(key="dedicated", value="", effect="NoSchedule")
    assert not tolerates_taints([gpu_taint], [tol])
    assert tolerates_taints([empty_taint], [tol])


def test_init_containers_max_semantics():
    pod = Pod.from_dict({
        "metadata": {"name": "p"},
        "spec": {
            "containers": [{"name": "c", "resources": {"requests": {"cpu": "100m", "memory": "64Mi"}}}],
            "initContainers": [
                {"name": "i1", "resources": {"requests": {"cpu": "4", "memory": "8Gi"}}},
                {"name": "i2", "resources": {"requests": {"cpu": "2"}}},
            ],
        },
    })
    req = pod.requests()
    assert req["cpu"] == 4000      # max(100, 4000, 2000)
    assert req["memory"] == 8192   # max(64, 8192)


def test_empty_node_selector_term_matches_nothing():
    assert not node_selector_terms_match({"zone": "a"}, [{}])
    # but a valid sibling term still matches (OR semantics)
    terms = [{}, {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}]
    assert node_selector_terms_match({"zone": "a"}, terms)


def test_gpu_resource_form_participates_in_fit():
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from tests.conftest import make_node, make_pod

    # node without GPUs; pod requests the gpu-mem *resource* form
    cluster = ClusterResources()
    cluster.nodes = [make_node("cpu-only")]
    app = ClusterResources()
    pod = Pod.from_dict({
        "metadata": {"name": "gpu-pod", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "resources": {
            "requests": {"cpu": "100m", "alibabacloud.com/gpu-mem": "8"}}}]},
    })
    app.pods = [pod]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(res.unscheduled_pods) == 1
    assert "Insufficient alibabacloud.com/gpu-mem" in res.unscheduled_pods[0].reason


def test_pinned_multi_gpu_filter_matches_reference_capacity_check():
    """ADVICE r2: the Filter capacity precheck is total-node-GPU-mem >= the
    pod's PER-GPU mem (open-gpu-share.go:64-67), not mem*count — a pinned
    multi-GPU pod whose total request exceeds node capacity still passes the
    reference Filter (AllocateGpuId returns the pinned id verbatim,
    gpunodeinfo.go:247-253)."""
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from open_simulator_tpu.k8s.objects import ANNO_GPU_INDEX
    from tests.test_gpu_share import gpu_node, gpu_pod

    # node total GPU mem = 2*8 = 16 >= per-GPU mem 10, but < mem*cnt = 30
    pinned = gpu_pod("pinned3", mem=10, count=3)
    pinned.meta.annotations[ANNO_GPU_INDEX] = "0-0-1"
    cluster = ClusterResources()
    cluster.nodes = [gpu_node("g0", gpus=2, mem_per_gpu=8)]
    app = ClusterResources()
    app.pods = [pinned]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert not res.unscheduled_pods
    assert res.placements()["default/pinned3"] == "g0"


def test_unpinned_multi_gpu_still_requires_allocation_feasibility():
    """The relaxed capacity precheck must not leak: an UNPINNED pod with the
    same shape still fails (two-pointer allocation infeasible)."""
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from tests.test_gpu_share import gpu_node, gpu_pod

    cluster = ClusterResources()
    cluster.nodes = [gpu_node("g0", gpus=2, mem_per_gpu=8)]
    app = ClusterResources()
    app.pods = [gpu_pod("wants3", mem=10, count=3)]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(res.unscheduled_pods) == 1


def test_preemption_host_model_honors_pinned_gpu_bypass():
    """ADVICE r2: the victim-selection fits() must mirror gpu_fit's pinned
    bypass — a pinned preemptor whose two-pointer allocation is infeasible
    (but whose pinned id the scan admits) must still win its preemption."""
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from open_simulator_tpu.k8s.objects import ANNO_GPU_INDEX, PriorityClass
    from tests.test_gpu_share import gpu_node, gpu_pod
    from tests.conftest import make_pod

    cluster = ClusterResources()
    # 1 device x 16 GiB; cpu sized so high+low cannot coexist
    cluster.nodes = [gpu_node("g0", gpus=1, mem_per_gpu=16)]
    cluster.nodes[0].allocatable["cpu"] = 2000.0
    cluster.priority_classes = [PriorityClass.from_dict({
        "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
        "metadata": {"name": "crit"}, "value": 1000,
    })]
    app1 = ClusterResources()
    app1.pods = [make_pod("low", cpu="1500m")]
    app2 = ClusterResources()
    # cnt=2 x mem=10: slots = floor(16/10) = 1 < 2 -> two-pointer infeasible,
    # but the pinned gpu-index bypasses that check in gpu_fit
    high = gpu_pod("high", mem=10, count=2, cpu="1500m")
    high.meta.annotations[ANNO_GPU_INDEX] = "0-0"
    high.priority_class_name = "crit"
    app2.pods = [high]
    res = simulate(
        cluster,
        [AppResource(name="a", resources=app1), AppResource(name="b", resources=app2)],
    )
    assert res.placements().get("default/high") == "g0"
    assert any(p.pod.meta.name == "low" and "preempted" in p.reason
               for p in res.unscheduled_pods)


def test_out_of_range_gpu_index_pin_warns(caplog):
    """ADVICE r2: a gpu-index token >= max_gpus_per_node used to be silently
    dropped; the encoder now logs the drop like the reference's invalid-id
    warning (gpunodeinfo.go:252)."""
    import logging

    from open_simulator_tpu.encode.snapshot import encode_cluster
    from open_simulator_tpu.k8s.objects import ANNO_GPU_INDEX
    from tests.test_gpu_share import gpu_node, gpu_pod

    pinned = gpu_pod("pin-high", mem=4)
    pinned.meta.annotations[ANNO_GPU_INDEX] = "9"  # default G = 8
    with caplog.at_level(logging.WARNING, logger="open_simulator_tpu.encode.snapshot"):
        encode_cluster([gpu_node("g0", gpus=2, mem_per_gpu=16)], [pinned])
    assert any("gpu-index" in r.message and "'9'" in r.message for r in caplog.records)


def test_pinned_gpu_preemptor_not_planned_onto_gpuless_node():
    """Review follow-up: the pinned bypass must NOT skip the capacity/device
    precheck — otherwise the host model plans a preemption on a GPU-less
    node that the rescan's gpu_fit always rejects, permanently blocking the
    preemptor from the viable GPU node."""
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources
    from open_simulator_tpu.k8s.objects import ANNO_GPU_INDEX, PriorityClass
    from tests.test_gpu_share import gpu_node, gpu_pod
    from tests.conftest import make_pod

    cluster = ClusterResources()
    from tests.conftest import make_node
    # node A: no GPUs, cheap victim; node B: has the GPU but pricier victim
    node_a = make_node("a0", cpu_m=2000)
    node_b = gpu_node("b0", gpus=1, mem_per_gpu=16)
    node_b.allocatable["cpu"] = 2000.0
    cluster.nodes = [node_a, node_b]
    cluster.priority_classes = [PriorityClass.from_dict({
        "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
        "metadata": {"name": "crit"}, "value": 1000,
    })]
    app1 = ClusterResources()
    low_a = make_pod("low-a", cpu="1500m", node_name="a0")
    low_b = make_pod("low-b", cpu="1500m", node_selector={"gpu": "true"})
    app1.pods = [low_a, low_b]
    app2 = ClusterResources()
    high = gpu_pod("high", mem=10, count=2, cpu="1500m")
    high.meta.annotations[ANNO_GPU_INDEX] = "0-0"
    high.priority_class_name = "crit"
    app2.pods = [high]
    res = simulate(
        cluster,
        [AppResource(name="a", resources=app1), AppResource(name="b", resources=app2)],
    )
    assert res.placements().get("default/high") == "b0"


def test_make_valid_pod_apiserver_validation_subset():
    """ValidatePodCreate-subset widening (reference runs the full vendored
    validation, pkg/utils/utils.go:408): DNS names, duplicate containers,
    restartPolicy/toleration/selector-operator enums, spread shapes."""
    import pytest

    from open_simulator_tpu.k8s.loader import PodValidationError, make_valid_pod
    from open_simulator_tpu.k8s.objects import Pod

    def pod(meta=None, spec=None):
        d = {"metadata": {"name": "ok", **(meta or {})},
             "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                      **(spec or {})}}
        return Pod.from_dict(d)

    make_valid_pod(pod())  # baseline valid
    with pytest.raises(PodValidationError, match="DNS-1123"):
        make_valid_pod(pod(meta={"name": "Bad_Name"}))
    with pytest.raises(PodValidationError, match="duplicate container"):
        make_valid_pod(pod(spec={"containers": [
            {"name": "c", "resources": {}}, {"name": "c", "resources": {}}]}))
    with pytest.raises(PodValidationError, match="restartPolicy"):
        make_valid_pod(pod(spec={"restartPolicy": "Sometimes"}))
    with pytest.raises(PodValidationError, match="invalid operator"):
        make_valid_pod(pod(spec={"tolerations": [{"key": "k", "operator": "Matches"}]}))
    with pytest.raises(PodValidationError, match="maxSkew"):
        make_valid_pod(pod(spec={"topologySpreadConstraints": [{
            "maxSkew": 0, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule"}]}))
    with pytest.raises(PodValidationError, match="whenUnsatisfiable"):
        make_valid_pod(pod(spec={"topologySpreadConstraints": [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "Perhaps"}]}))
    with pytest.raises(PodValidationError, match="requires values"):
        make_valid_pod(pod(spec={"affinity": {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "k", "operator": "In"}]}]}}}}))
    with pytest.raises(PodValidationError, match="must not set values"):
        make_valid_pod(pod(spec={"affinity": {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "k", "operator": "Exists", "values": ["x"]}]}]}}}}))


def test_make_valid_pod_widened_checks():
    """Late-r4 widening toward vendored ValidatePodCreate: label syntax,
    hostPort ranges/duplicates/protocols, duplicate volume names, nodeName
    syntax, name length, and the label-selector op set (no Gt/Lt — those
    are node-selector-exclusive)."""
    import pytest

    from open_simulator_tpu.k8s.loader import PodValidationError, make_valid_pod
    from open_simulator_tpu.k8s.objects import Pod

    def pod(meta=None, spec=None):
        d = {"metadata": {"name": "ok", **(meta or {})},
             "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "1"}}}],
                      **(spec or {})}}
        return Pod.from_dict(d)

    with pytest.raises(PodValidationError, match="DNS-1123"):
        make_valid_pod(pod(meta={"name": "a" * 254}))
    with pytest.raises(PodValidationError, match="invalid label key"):
        make_valid_pod(pod(meta={"labels": {"-bad": "v"}}))
    with pytest.raises(PodValidationError, match="invalid label value"):
        make_valid_pod(pod(meta={"labels": {"app": "x" * 64}}))
    make_valid_pod(pod(meta={"labels": {"example.com/app": "web_1.2-a"}}))
    with pytest.raises(PodValidationError, match="nodeName"):
        make_valid_pod(pod(spec={"nodeName": "Bad_Node"}))
    with pytest.raises(PodValidationError, match="out of range"):
        make_valid_pod(pod(spec={"containers": [{
            "name": "c", "ports": [{"hostPort": 70000}]}]}))
    with pytest.raises(PodValidationError, match="port protocol"):
        make_valid_pod(pod(spec={"containers": [{
            "name": "c", "ports": [{"hostPort": 80, "protocol": "ICMP"}]}]}))
    with pytest.raises(PodValidationError, match="duplicate hostPort"):
        make_valid_pod(pod(spec={"containers": [{
            "name": "c",
            "ports": [{"hostPort": 80}, {"hostPort": 80}]}]}))
    # same hostPort under different protocols is legal
    make_valid_pod(pod(spec={"containers": [{
        "name": "c",
        "ports": [{"hostPort": 80}, {"hostPort": 80, "protocol": "UDP"}]}]}))
    # vendored grouping: init containers run sequentially, so an init
    # container may share a hostPort with a regular container (and with
    # another init container) — only regular containers conflict
    make_valid_pod(pod(spec={
        "containers": [{"name": "c", "ports": [{"hostPort": 80}]}],
        "initContainers": [
            {"name": "i1", "ports": [{"hostPort": 80}]},
            {"name": "i2", "ports": [{"hostPort": 80}]},
        ]}))
    # protocol enum applies to ALL declared ports, not just hostPorts
    with pytest.raises(PodValidationError, match="port protocol"):
        make_valid_pod(pod(spec={"containers": [{
            "name": "c", "ports": [{"containerPort": 8080, "protocol": "ICMP"}]}]}))
    with pytest.raises(PodValidationError, match="containerPort"):
        make_valid_pod(pod(spec={"containers": [{
            "name": "c", "ports": [{"containerPort": 0}]}]}))
    with pytest.raises(PodValidationError, match="duplicate volume"):
        make_valid_pod(pod(spec={"volumes": [
            {"name": "v", "emptyDir": {}}, {"name": "v", "emptyDir": {}}]}))
    with pytest.raises(PodValidationError, match="labelSelector operator"):
        make_valid_pod(pod(spec={"affinity": {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "zone",
                "labelSelector": {"matchExpressions": [
                    {"key": "k", "operator": "Gt", "values": ["1"]}]}}]}}}))
    with pytest.raises(PodValidationError, match="labelSelector In requires"):
        make_valid_pod(pod(spec={"topologySpreadConstraints": [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "ScheduleAnyway",
            "labelSelector": {"matchExpressions": [
                {"key": "k", "operator": "In"}]}}]}))


def test_make_valid_node_name_and_labels():
    """Node-side validation (vendored ValidateNode subset): DNS-1123 name
    and metadata.labels syntax."""
    import pytest

    from open_simulator_tpu.k8s.loader import PodValidationError, make_valid_node
    from open_simulator_tpu.k8s.objects import Node

    def node(name="n0", labels=None):
        return Node.from_dict({
            "metadata": {"name": name, "labels": labels or {}},
            "status": {"allocatable": {"cpu": "1", "memory": "1Gi"}},
        })

    make_valid_node(node(labels={"node-role.kubernetes.io/master": ""}))
    with pytest.raises(PodValidationError, match="DNS-1123"):
        make_valid_node(node(name="Bad_Node"))
    with pytest.raises(PodValidationError, match="invalid label key"):
        make_valid_node(node(labels={"-bad": "v"}))


def test_namespace_is_dns1123_label_not_subdomain():
    """Review r4: namespaces are DNS-1123 LABELS (no dots, <=63 chars),
    stricter than object names (subdomains)."""
    import pytest

    from open_simulator_tpu.k8s.loader import PodValidationError, make_valid_pod
    from open_simulator_tpu.k8s.objects import Pod

    def pod(ns):
        return Pod.from_dict({
            "metadata": {"name": "ok", "namespace": ns},
            "spec": {"containers": [{"name": "c", "resources": {}}]}})

    make_valid_pod(pod("prod"))
    make_valid_pod(Pod.from_dict({
        "metadata": {"name": "ok.dotted.name", "namespace": "prod"},
        "spec": {"containers": [{"name": "c", "resources": {}}]}}))  # names may dot
    with pytest.raises(PodValidationError, match="DNS-1123 label"):
        make_valid_pod(pod("team.prod"))
    with pytest.raises(PodValidationError, match="DNS-1123 label"):
        make_valid_pod(pod("x" * 64))


def test_dns1123_subdomain_validates_per_label():
    """Review r4: each dot-separated label must independently satisfy
    DNS-1123 — 'a..b' / 'a.-b' are rejected like the real apiserver."""
    import pytest

    from open_simulator_tpu.k8s.loader import PodValidationError, make_valid_pod
    from open_simulator_tpu.k8s.objects import Pod

    def pod(name):
        return Pod.from_dict({
            "metadata": {"name": name},
            "spec": {"containers": [{"name": "c", "resources": {}}]}})

    make_valid_pod(pod("a.b-c.d"))
    for bad in ("a..b", "a.-b", "a-.b", ".a", "a."):
        with pytest.raises(PodValidationError, match="DNS-1123"):
            make_valid_pod(pod(bad))
