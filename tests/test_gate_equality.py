"""Gate-equality: the make_config feature gates compile ops out of the scan
step, claiming "results identical" (engine/scheduler.py EngineConfig docs).
These tests force every gate ON against snapshots whose autodetection turns
some OFF and assert bit-identical assignments and reason counts — the
regression VERDICT r3 flagged as untested.

Also covers the dom_count carry vs per-node group_count path: a zone-only
spread snapshot autodetects spread_hostname=False (no [N, S] carry); forcing
spread_hostname=True runs the same constraints through the hostname-capable
gc path and must agree exactly.
"""

import numpy as np
import pytest

from open_simulator_tpu.encode.snapshot import EncodeOptions, encode_cluster
from open_simulator_tpu.engine.scheduler import (
    device_arrays,
    make_config,
    schedule_pods,
)
from tests.conftest import make_node, make_pod

ALL_GATES = dict(
    enable_ports=True,
    enable_pod_affinity=True,
    enable_anti_affinity=True,
    enable_spread_hard=True,
    enable_spread_soft=True,
    enable_pref=True,
    enable_node_aff_score=True,
    enable_taint_score=True,
    spread_hostname=True,
    enable_unsched=True,
    enable_class_aff=True,
    enable_class_taint=True,
)


def _zone_nodes(n):
    return [
        make_node(f"n{i}", cpu_m=8000, mem_mib=16384,
                  labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
        for i in range(n)
    ]


def _run(snapshot, **overrides):
    cfg = make_config(snapshot, **overrides)
    arrs = device_arrays(snapshot)
    out = schedule_pods(arrs, arrs.active, cfg)
    return np.asarray(out.node), np.asarray(out.fail_counts), cfg


def assert_same_result(snapshot, **forced):
    nodes_auto, fails_auto, cfg_auto = _run(snapshot)
    nodes_on, fails_on, cfg_on = _run(snapshot, **forced)
    assert cfg_auto != cfg_on, "test must actually flip at least one gate"
    np.testing.assert_array_equal(nodes_auto, nodes_on)
    np.testing.assert_array_equal(fails_auto, fails_on)


def test_plain_fit_snapshot_all_gates_forced_on():
    """cpu/mem-only pods: autodetect turns every optional op off; forcing
    all on must not change a single assignment or reason row."""
    rng = np.random.RandomState(3)
    pods = [
        make_pod(f"p{i}", cpu=f"{rng.randint(100, 1500)}m",
                 mem=f"{rng.randint(64, 1024)}Mi", labels={"app": f"a{i % 4}"})
        for i in range(40)
    ]
    snap = encode_cluster(_zone_nodes(6), pods)
    cfg = make_config(snap)
    assert not cfg.enable_ports and not cfg.enable_pod_affinity
    assert not cfg.enable_anti_affinity and not cfg.enable_pref
    assert_same_result(snap, **ALL_GATES)


def test_soft_spread_snapshot_gates_forced_on():
    """Zone ScheduleAnyway spread (the bench shape): spread_soft stays on,
    everything else off; force-all-on must agree, including the hard-spread
    filter path running against zero hard constraints."""
    rng = np.random.RandomState(4)
    spread = [{
        "maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "ScheduleAnyway",
        "labelSelector": {"matchLabels": {"app": "a0"}},
    }]
    pods = [
        make_pod(f"p{i}", cpu=f"{rng.randint(100, 900)}m", mem="256Mi",
                 labels={"app": "a0"}, spread=spread)
        for i in range(30)
    ]
    snap = encode_cluster(_zone_nodes(6), pods)
    cfg = make_config(snap)
    assert cfg.enable_spread_soft and not cfg.enable_spread_hard
    assert not cfg.spread_hostname and not cfg.needs_group_count
    assert_same_result(snap, **ALL_GATES)


def test_zone_spread_dom_carry_vs_hostname_gc_path():
    """The dom_count fast path (no per-node group_count carry) vs the
    gc-capable path must be bit-identical for zone-keyed constraints, hard
    and soft."""
    rng = np.random.RandomState(5)
    pods = []
    for i in range(36):
        spread = [{
            "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule" if i % 2 else "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": f"a{i % 2}"}},
        }]
        pods.append(make_pod(
            f"p{i}", cpu=f"{rng.randint(100, 700)}m", mem="128Mi",
            labels={"app": f"a{i % 2}"}, spread=spread))
    snap = encode_cluster(_zone_nodes(9), pods)
    cfg = make_config(snap)
    assert cfg.enable_spread_hard and cfg.enable_spread_soft
    assert not cfg.spread_hostname
    assert_same_result(snap, spread_hostname=True)


def test_constraint_rich_snapshot_matches_forced_on():
    """A snapshot using ports + anti-affinity + hostname hard spread +
    preferred affinity: most gates already on; forcing the remainder
    (pod-affinity, taint score, ...) must still be identical."""
    rng = np.random.RandomState(6)
    pods = []
    for i in range(24):
        kw = dict(cpu=f"{rng.randint(100, 800)}m", mem="128Mi",
                  labels={"app": f"a{i % 3}"})
        if i % 4 == 0:
            kw["host_ports"] = [8000 + (i % 2)]
        if i % 5 == 0:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }],
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 5,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": f"a{(i + 1) % 3}"}},
                            "topologyKey": "topology.kubernetes.io/zone",
                        },
                    }],
                },
            }
        if i % 6 == 0:
            kw["spread"] = [{
                "maxSkew": 3, "topologyKey": "kubernetes.io/hostname",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
            }]
        pods.append(make_pod(f"p{i}", **kw))
    snap = encode_cluster(_zone_nodes(8), pods)
    cfg = make_config(snap)
    assert cfg.enable_anti_affinity and cfg.enable_ports and cfg.spread_hostname
    assert not cfg.enable_pod_affinity  # no required pod-affinity terms
    assert_same_result(snap, **ALL_GATES)


@pytest.mark.parametrize("max_new", [0, 4])
def test_gates_hold_under_inactive_padded_nodes(max_new):
    """Gate equality with padded new-node slots inactive (the sweep's lane-0
    shape): inactive nodes must not leak into either path's aggregations."""
    rng = np.random.RandomState(7)
    spread = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "a0"}},
    }]
    pods = [
        make_pod(f"p{i}", cpu=f"{rng.randint(200, 900)}m", mem="256Mi",
                 labels={"app": "a0"}, spread=spread)
        for i in range(18)
    ]
    opts = None
    if max_new:
        opts = EncodeOptions(max_new_nodes=max_new,
                             new_node_template=_zone_nodes(1)[0])
    snap = encode_cluster(_zone_nodes(6), pods, opts)
    assert_same_result(snap, **ALL_GATES)


def test_forced_prefix_hoisting_bit_equal():
    """A leading run of bound (spec.nodeName) pods applied as one batched
    scatter must reproduce the sequential scan bit-for-bit — assignments,
    carry state, and the downstream unbound pods' decisions (which read
    the carry the prefix built: counts, paints, ports, spread domains)."""
    rng = np.random.RandomState(11)
    nodes = _zone_nodes(8)
    pods = []
    # 30 bound pods with the full constraint surface painted into the carry
    for i in range(30):
        kw = dict(cpu=f"{rng.randint(100, 600)}m", mem="128Mi",
                  labels={"app": f"a{i % 3}", "anti": f"g{i % 5}"},
                  node_name=f"n{i % 8}")
        if i % 3 == 0:
            kw["host_ports"] = [7000 + i]
        if i % 4 == 0:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {"anti": f"g{i % 5}"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }],
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 7,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": f"a{(i + 1) % 3}"}},
                            "topologyKey": "topology.kubernetes.io/zone",
                        },
                    }],
                },
            }
        pods.append(make_pod(f"bound{i}", **kw))
    # then unbound pods whose decisions depend on the prefix's carry
    for i in range(24):
        spread = [{
            "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule" if i % 2 else "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": f"a{i % 3}"}},
        }]
        kw = dict(cpu=f"{rng.randint(100, 500)}m", mem="128Mi",
                  labels={"app": f"a{i % 3}", "anti": f"g{i % 5}"}, spread=spread)
        if i % 3 == 0:
            kw["host_ports"] = [7000 + (i % 30)]
        if i % 4 == 1:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {"anti": f"g{i % 5}"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }],
                },
            }
        pods.append(make_pod(f"free{i}", **kw))
    snap = encode_cluster(nodes, pods)
    cfg_auto = make_config(snap)
    assert cfg_auto.forced_prefix == 30
    nodes_h, fails_h, _ = _run(snap)
    nodes_f, fails_f, _ = _run(snap, forced_prefix=0)
    np.testing.assert_array_equal(nodes_h, nodes_f)
    # prefix rows report zero fail counts (their binding is predetermined;
    # decode never reads fail rows of scheduled pods) — compare the rest
    np.testing.assert_array_equal(fails_h[30:], fails_f[30:])

    # carry state equality too
    from open_simulator_tpu.engine.scheduler import device_arrays, schedule_pods

    arrs = device_arrays(snap)
    out_h = schedule_pods(arrs, arrs.active, cfg_auto)
    out_f = schedule_pods(arrs, arrs.active, cfg_auto._replace(forced_prefix=0))
    for a, b in zip(out_h.state, out_f.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_slot_paint_vs_dense_bit_equal():
    """The sparse-slot carry updates (group_count/term_block/dom_count
    column DUS + per-hit-term blocked gathers, EngineConfig.slot_paint)
    must be bit-identical to the dense forms — each column is touched at
    most once per pod, so the adds are the same adds."""
    rng = np.random.RandomState(7)
    pods = []
    for i in range(40):
        kw = dict(cpu=f"{rng.randint(100, 900)}m", mem="256Mi",
                  labels={"app": f"a{i % 4}", "anti": f"g{i % 7}"})
        if i % 3 == 0:
            kw["affinity"] = {
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {"anti": f"g{i % 7}"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }],
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 4,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": f"a{(i + 1) % 4}"}},
                            "topologyKey": "topology.kubernetes.io/zone",
                        },
                    }],
                },
            }
        kw["spread"] = [{
            "maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule" if i % 2 else "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": f"a{i % 4}"}},
        }]
        pods.append(make_pod(f"p{i}", **kw))
    snap = encode_cluster(_zone_nodes(8), pods)
    cfg = make_config(snap)
    assert cfg.slot_paint and cfg.enable_anti_affinity and cfg.enable_pref

    nodes_slot, fails_slot, _ = _run(snap)
    nodes_dense, fails_dense, _ = _run(snap, slot_paint=False)
    np.testing.assert_array_equal(nodes_slot, nodes_dense)
    np.testing.assert_array_equal(fails_slot, fails_dense)

    # final carries must agree too (the slot updates ARE the carry)
    arrs = device_arrays(snap)
    out_s = schedule_pods(arrs, arrs.active, make_config(snap))
    out_d = schedule_pods(arrs, arrs.active, make_config(snap, slot_paint=False))
    for a, b in zip(out_s.state, out_d.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
