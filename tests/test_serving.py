"""Inference-grade serving tests (server/serving.py, ARCHITECTURE.md §16).

Covers the four tentpole contracts of ISSUE 12:

* resident snapshot cache: content-addressed admission, LRU +
  byte-budget eviction that DEGRADES (re-transfer / transient serve,
  never a 500), concurrent eviction vs touch without deadlock;
* delta requests: structured 400s for every malformed diff (incl. a
  ~50-seed mutation fuzz over both endpoints) and bit-identical
  placement digests between a delta-applied overlay and a cold full
  re-encode of the diffed cluster;
* fault-isolated coalescing: concurrent probes of one snapshot merge
  into one launch whose per-lane digests equal their singleton runs,
  a poisoned lane (deadline, audit) fails ALONE;
* the multi-worker queue: member-counted Retry-After accounting,
  crashed-worker replacement, long jobs not starving short ones.
"""

import json
import random
import textwrap
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from open_simulator_tpu import telemetry
from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.resilience import lifecycle
from open_simulator_tpu.server import serving
from open_simulator_tpu.server.rest import SimulationServer, _make_handler

CLUSTER_YAML = textwrap.dedent("""
    apiVersion: v1
    kind: Node
    metadata: {name: s0, labels: {topology.kubernetes.io/zone: z0}}
    status:
      allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
    ---
    apiVersion: v1
    kind: Node
    metadata: {name: s1, labels: {topology.kubernetes.io/zone: z0}}
    status:
      allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
    ---
    apiVersion: v1
    kind: Node
    metadata: {name: s2, labels: {topology.kubernetes.io/zone: z1}}
    status:
      allocatable: {cpu: "4", memory: 8Gi, pods: "110"}
    ---
    apiVersion: apps/v1
    kind: Deployment
    metadata: {name: existing, namespace: default}
    spec:
      replicas: 4
      selector: {matchLabels: {app: existing}}
      template:
        metadata: {labels: {app: existing}}
        spec:
          containers:
            - name: c
              image: registry.local/e:1
              resources: {requests: {cpu: "2", memory: 2Gi}}
""")

NODE_SPEC_YAML = textwrap.dedent("""
    apiVersion: v1
    kind: Node
    metadata: {name: template}
    status:
      allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
""")


def _mini_server(**kw):
    srv = SimulationServer(**kw)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(url, payload):
    """POST returning (status, body) without raising."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def box():
    srv, httpd, url = _mini_server()
    yield srv, url
    httpd.shutdown()


@pytest.fixture(scope="module")
def base_digest(box):
    """The shared cluster admitted once; most tests probe this digest."""
    _, url = box
    status, out = _post(url + "/api/simulate",
                        {"cluster": {"yaml": CLUSTER_YAML}})
    assert status == 200, out
    return out["snapshot_digest"]


# ---- delta validation (unit) ---------------------------------------------


def test_parse_delta_validation():
    ok = serving.parse_delta({"add_nodes": 2, "remove_nodes": ["n1"],
                              "remove_pods": ["default/a-0"]})
    assert ok.add_nodes == 2 and ok.remove_nodes == ("n1",)
    assert not ok.mask_only      # pod diffs rewrite the forced column
    assert serving.parse_delta(None).empty
    assert serving.parse_delta({"add_nodes": 1}).mask_only
    for raw, field in [
        (["x"], "delta"),                                # wrong container
        ({"add_nodes": -1}, "delta.add_nodes"),          # negative quantity
        ({"add_nodes": True}, "delta.add_nodes"),        # bool masquerade
        ({"add_nodes": "2"}, "delta.add_nodes"),         # stringly int
        ({"remove_nodes": "n1"}, "delta.remove_nodes"),  # not a list
        ({"remove_nodes": [""]}, "delta.remove_nodes"),  # empty name
        ({"remove_pods": [3]}, "delta.remove_pods"),     # wrong item type
        ({"remove_node": ["n"]}, "delta.remove_node"),   # truncated key
        ({"add_apps": "yaml"}, "delta.add_apps"),        # not a list
        ({"add_apps": [{"name": "a"}]}, "delta.add_apps[0].yaml"),
    ]:
        with pytest.raises(SimulationError) as ei:
            serving.parse_delta(raw)
        assert ei.value.code == "E_BAD_REQUEST"
        assert ei.value.field == field, (raw, ei.value.field)


# ---- resident cache ------------------------------------------------------


def test_content_addressed_admission(box, base_digest):
    """Identical full POSTs land on ONE digest (deterministic template
    clone names included) and later base probes are cache hits."""
    srv, url = box
    hits0 = telemetry.counter("simon_resident_total", labelnames=("event",)).value(event="hit")
    s, again = _post(url + "/api/simulate", {"cluster": {"yaml": CLUSTER_YAML}})
    assert s == 200 and again["snapshot_digest"] == base_digest
    s2, probe = _post(url + "/api/simulate", {"base": base_digest,
                                              "placements": True})
    assert s2 == 200
    assert probe["digest"] == again["digest"]
    assert probe["placements"]           # full table on request
    assert telemetry.counter("simon_resident_total", labelnames=("event",)).value(
        event="hit") > hits0
    assert srv._snapshots.stats()["resident"] >= 1


def test_base_and_cluster_mutually_exclusive(box, base_digest):
    _, url = box
    s, out = _post(url + "/api/simulate",
                   {"base": base_digest, "cluster": {"yaml": CLUSTER_YAML}})
    assert s == 400 and out["field"] == "cluster"


def test_unknown_base_digest_400(box):
    _, url = box
    s, out = _post(url + "/api/simulate", {"base": "feedbeef00000000"})
    assert s == 400 and out["field"] == "base"
    assert "re-POST" in out["hint"]


# ---- delta == cold re-encode ---------------------------------------------


def test_delta_remove_node_matches_cold_reencode(box, base_digest):
    """Deactivating s2 via delta must place exactly like a cold full
    re-encode of the cluster WITHOUT s2 (the index-free digest)."""
    _, url = box
    s, hot = _post(url + "/api/simulate",
                   {"base": base_digest, "delta": {"remove_nodes": ["s2"]},
                    "audit": True})
    assert s == 200, hot
    cold_yaml = "\n---\n".join(
        doc for doc in CLUSTER_YAML.split("---")
        if "name: s2" not in doc)
    s2, cold = _post(url + "/api/simulate", {"cluster": {"yaml": cold_yaml}})
    assert s2 == 200, cold
    assert hot["digest"] == cold["digest"]
    assert hot["placed"] == cold["placed"]
    assert hot["active_nodes"] == cold["active_nodes"] == 2


def test_delta_remove_pods_matches_cold_reencode(box, base_digest):
    """Sentinelling default/existing-3 out must digest like a cold
    re-encode with replicas: 3 (same first three pod keys)."""
    _, url = box
    s, hot = _post(url + "/api/simulate",
                   {"base": base_digest,
                    "delta": {"remove_pods": ["default/existing-3"]}})
    assert s == 200, hot
    s2, cold = _post(url + "/api/simulate",
                     {"cluster": {"yaml": CLUSTER_YAML.replace(
                         "replicas: 4", "replicas: 3")}})
    assert s2 == 200, cold
    assert hot["digest"] == cold["digest"]
    assert hot["placed"] == cold["placed"] == 3


def test_delta_add_nodes_matches_cold_real_node(box):
    """Activating template slot sim-new-000 must place exactly like a
    cold encode where the SAME node is a real cluster member (the
    engine never reads is_new_node — slots are just inactive nodes)."""
    _, url = box
    body = {"cluster": {"yaml": CLUSTER_YAML.replace(
                "replicas: 4", "replicas: 9")},
            "new_node": {"spec_yaml": NODE_SPEC_YAML}, "max_new_nodes": 2}
    s, base = _post(url + "/api/simulate", body)
    assert s == 200, base
    s1, hot = _post(url + "/api/simulate",
                    {"base": base["snapshot_digest"],
                     "delta": {"add_nodes": 1}, "audit": True})
    assert s1 == 200, hot
    assert hot["active_nodes"] == 4
    cold_node = textwrap.dedent("""
        apiVersion: v1
        kind: Node
        metadata:
          name: sim-new-000
          labels:
            simon.tpu/new-node: "true"
            kubernetes.io/hostname: sim-new-000
        status:
          allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
    """)
    s2, cold = _post(url + "/api/simulate", {"cluster": {"yaml": (
        CLUSTER_YAML.replace("replicas: 4", "replicas: 9")
        + "\n---\n" + cold_node)}})
    assert s2 == 200, cold
    assert hot["digest"] == cold["digest"]
    assert hot["placed"] == cold["placed"]


def _cache_state(srv):
    """Canonical cache state: an LRU touch (rejected requests still look
    their base up) reorders the listing but mutates nothing."""
    st = srv._snapshots.stats()
    st["snapshots"] = sorted(st["snapshots"], key=lambda e: e["digest"])
    return st


def test_delta_dangling_refs_are_400s(box, base_digest):
    srv, url = box
    before = _cache_state(srv)
    cases = [
        ({"remove_nodes": ["ghost"]}, "delta.remove_nodes"),
        ({"remove_pods": ["default/ghost-0"]}, "delta.remove_pods"),
        ({"add_nodes": 5}, "delta.add_nodes"),   # no free slots encoded
    ]
    for delta, field in cases:
        s, out = _post(url + "/api/simulate",
                       {"base": base_digest, "delta": delta})
        assert s == 400 and out["field"] == field, (delta, out)
    assert _cache_state(srv) == before   # rejections never mutate


# ---- mutation fuzz (ISSUE 12 satellite) ----------------------------------


def _mutate_body(rng: random.Random, digest: str):
    """One seeded mutation of a valid delta request body."""
    body = {"base": digest,
            "delta": {"add_nodes": 0, "remove_nodes": ["s1"],
                      "remove_pods": ["default/existing-0"]}}
    kind = rng.randrange(10)
    if kind == 0:                                    # bogus base digest
        body["base"] = "".join(rng.choice("0123456789abcdef")
                               for _ in range(16))
    elif kind == 1:                                  # wrong base type
        body["base"] = rng.choice([17, [], {"d": 1}, True, ""])
    elif kind == 2:                                  # dangling node ref
        body["delta"]["remove_nodes"] = [f"ghost-{rng.randrange(99)}"]
    elif kind == 3:                                  # dangling pod ref
        body["delta"]["remove_pods"] = [f"ns/ghost-{rng.randrange(99)}"]
    elif kind == 4:                                  # negative / huge adds
        body["delta"]["add_nodes"] = rng.choice([-1, -17, 10**9])
    elif kind == 5:                                  # wrong quantity types
        body["delta"]["add_nodes"] = rng.choice(
            ["2", 1.5, None, True, [1]])
    elif kind == 6:                                  # truncated diff keys
        body["delta"] = {rng.choice(["remove_node", "add_node", "rm",
                                     "remove_podz"]): ["x"]}
    elif kind == 7:                                  # wrong container types
        body["delta"] = rng.choice(["remove_nodes", 42, ["s1"], True])
    elif kind == 8:                                  # malformed add_apps
        body["delta"] = {"add_apps": rng.choice(
            ["app", [{"name": "a"}], [{"yaml": ""}], [42],
             [{"name": "a", "yaml": "{{not yaml"}]])}
    else:                                            # item-type poison
        body["delta"]["remove_nodes"] = rng.choice(
            [[None], [3], [["s1"]], "s1", [""]])
    return body


def test_fuzz_delta_bodies_never_500(box, base_digest):
    """~50 seeded mutations against BOTH serving endpoints: structured
    4xx, never a 500, resident cache state untouched by rejections."""
    srv, url = box
    before = _cache_state(srv)
    statuses = set()
    for seed in range(50):
        rng = random.Random(seed)
        body = _mutate_body(rng, base_digest)
        path = rng.choice(["/api/simulate", "/api/capacity"])
        if path == "/api/capacity":
            body["sweep_mode"] = "exhaustive"
        s, out = _post(url + path, body)
        statuses.add(s)
        assert s != 500, (seed, path, body, out)
        if s >= 400:
            assert out.get("code"), (seed, path, body, out)
            assert _cache_state(srv) == before, (seed, path, body)
    assert statuses >= {400}   # the corpus actually exercised rejections


# ---- coalescing ----------------------------------------------------------


def _wait_queued(srv, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if srv._queue.stats()["queued"] >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"queue never reached {n}: {srv._queue.stats()}")


def test_coalesced_digests_equal_singleton(base_digest):
    """Three concurrent base probes + one capacity sweep against one
    snapshot merge into ONE launch; every caller's digest equals its
    singleton run (the capacity count-0 lane IS the plain probe)."""
    srv, httpd, url = _mini_server()
    try:
        s, out = _post(url + "/api/simulate",
                       {"cluster": {"yaml": CLUSTER_YAML}})
        assert s == 200
        digest = out["snapshot_digest"]
        singleton = out["digest"]

        release = threading.Event()
        srv.deploy_apps = lambda body: (release.wait(10.0), {})[1]
        results = []
        lock = threading.Lock()

        def probe(payload, path="/api/simulate"):
            r = _post(url + path, payload)
            with lock:
                results.append((path, r))

        blocker = threading.Thread(
            target=probe, args=({"apps": []}, "/api/deploy-apps"))
        blocker.start()
        # wait for the blocker to be IN FLIGHT so the probes queue behind
        deadline = time.monotonic() + 5.0
        while srv._queue.stats()["in_flight"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        threads = [threading.Thread(target=probe, args=({"base": digest},))
                   for _ in range(3)]
        threads.append(threading.Thread(
            target=probe,
            args=({"base": digest, "sweep_mode": "exhaustive"},
                  "/api/capacity")))
        for t in threads:
            t.start()
        _wait_queued(srv, 4)
        release.set()
        blocker.join(15.0)
        for t in threads:
            t.join(15.0)
        assert len(results) == 5
        members = []
        for path, (status, body) in results:
            if path == "/api/deploy-apps":
                continue
            assert status == 200, body
            if path == "/api/simulate":
                assert body["digest"] == singleton
            else:
                # the capacity lane for count 0 is exactly the base probe
                assert body["counts"] == [0]
                assert body["lane_digests"] == [singleton]
            members.append(body["coalesced_members"])
        assert max(members) == 4, members   # one merged launch took all 4
    finally:
        httpd.shutdown()


def test_poisoned_lane_fails_alone():
    """One member blows its deadline while queued, another trips the
    placement auditor — each answers its OWN structured error while the
    sibling lanes return 200 with singleton-identical digests."""
    srv, httpd, url = _mini_server()
    real_audit = serving.audit_lane
    try:
        s, out = _post(url + "/api/simulate",
                       {"cluster": {"yaml": CLUSTER_YAML}})
        assert s == 200
        digest, singleton = out["snapshot_digest"], out["digest"]

        # auditor poison: only lanes that ASKED for an audit go through
        # audit_lane; make it reject deterministically
        def exploding_audit(entry, nodes_row, active, live, forced=None):
            raise SimulationError("injected audit violation",
                                  code="E_AUDIT", ref="test")

        serving.audit_lane = exploding_audit
        release = threading.Event()
        srv.deploy_apps = lambda body: (release.wait(10.0), {})[1]
        results = []
        lock = threading.Lock()

        def probe(payload):
            r = _post(url + "/api/simulate", payload)
            with lock:
                results.append((payload, r))

        blocker = threading.Thread(
            target=lambda: _post(url + "/api/deploy-apps", {"apps": []}))
        blocker.start()
        deadline = time.monotonic() + 5.0
        while srv._queue.stats()["in_flight"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        payloads = [{"base": digest},
                    {"base": digest, "deadline_s": 0.2},   # dies queued
                    {"base": digest, "audit": True},       # dies at audit
                    {"base": digest}]
        threads = [threading.Thread(target=probe, args=(p,))
                   for p in payloads]
        for t in threads:
            t.start()
        _wait_queued(srv, 4)
        time.sleep(0.3)          # the deadline_s member expires in queue
        release.set()
        blocker.join(15.0)
        for t in threads:
            t.join(15.0)
        serving.audit_lane = real_audit

        by_kind = {}
        for payload, (status, body) in results:
            if "deadline_s" in payload:
                by_kind["deadline"] = (status, body)
            elif payload.get("audit"):
                by_kind["audit"] = (status, body)
            else:
                by_kind.setdefault("ok", []).append((status, body))
        status, body = by_kind["deadline"]
        assert status == 504 and body["code"] == "E_DEADLINE"
        status, body = by_kind["audit"]
        assert status == 500 and body["code"] == "E_AUDIT"
        assert len(by_kind["ok"]) == 2
        for status, body in by_kind["ok"]:
            assert status == 200
            assert body["digest"] == singleton   # siblings unharmed
    finally:
        serving.audit_lane = real_audit
        httpd.shutdown()


# ---- eviction ------------------------------------------------------------


def _tiny_snapshot(n_pods=2, name="t"):
    from open_simulator_tpu.core import build_pod_sequence
    from open_simulator_tpu.encode.snapshot import encode_cluster
    from open_simulator_tpu.k8s.loader import (
        ClusterResources,
        demux_object,
        parse_yaml_documents,
    )

    docs = textwrap.dedent(f"""
        apiVersion: v1
        kind: Node
        metadata: {{name: {name}-n0}}
        status:
          allocatable: {{cpu: "8", memory: 16Gi, pods: "110"}}
        ---
        apiVersion: apps/v1
        kind: Deployment
        metadata: {{name: {name}, namespace: default}}
        spec:
          replicas: {n_pods}
          selector: {{matchLabels: {{app: {name}}}}}
          template:
            metadata: {{labels: {{app: {name}}}}}
            spec:
              containers:
                - name: c
                  resources: {{requests: {{cpu: "1", memory: 1Gi}}}}
    """)
    res = ClusterResources()
    for doc in parse_yaml_documents(docs):
        demux_object(doc, res)
    return encode_cluster(res.nodes, build_pod_sequence(res, []), None)


def test_byte_budget_eviction_degrades_never_500():
    """A 1-byte budget makes EVERY snapshot transient: probes still
    answer 200 (transient device arrays), nothing stays resident."""
    srv, httpd, url = _mini_server(max_resident_bytes=1)
    try:
        s, out = _post(url + "/api/simulate",
                       {"cluster": {"yaml": CLUSTER_YAML}})
        assert s == 200, out
        for _ in range(3):
            s2, probe = _post(url + "/api/simulate",
                              {"base": out["snapshot_digest"]})
            assert s2 == 200, probe
            assert probe["digest"] == out["digest"]
        stats = srv._snapshots.stats()
        assert stats["resident"] == 0       # over-budget: nothing cached
        assert stats["entries"] >= 1        # the host snapshot remains
        assert telemetry.counter("simon_resident_total", labelnames=("event",)).value(
            event="uncacheable") >= 3
    finally:
        httpd.shutdown()


def test_lru_eviction_keeps_budget_and_rehydrates():
    """Two snapshots, budget for one: the LRU victim drops its device
    arrays; touching it again rehydrates transparently and evicts the
    other — no request ever fails."""
    cache = serving.ResidentSnapshotCache(max_bytes=0)   # measure first
    a = cache.admit(_tiny_snapshot(2, "a"))
    cache.max_bytes = 10**9
    cache.device_arrays(a)
    one_entry = a.device_bytes
    assert one_entry > 0
    cache = serving.ResidentSnapshotCache(max_bytes=int(one_entry * 1.5))
    ea = cache.admit(_tiny_snapshot(2, "a"))
    eb = cache.admit(_tiny_snapshot(2, "b"))
    assert ea.digest != eb.digest
    cache.device_arrays(ea)
    cache.device_arrays(eb)                  # must evict ea
    assert eb.resident and not ea.resident
    cache.device_arrays(ea)                  # rehydrates, evicts eb
    assert ea.resident and not eb.resident
    assert telemetry.counter("simon_resident_total", labelnames=("event",)).value(
        event="eviction") >= 2
    cache.drop_all()
    assert telemetry.gauge("simon_resident_bytes").value() == 0
    assert telemetry.gauge("simon_resident_snapshots").value() == 0


def test_concurrent_eviction_hammer_no_deadlock():
    """N threads share two digests under a one-entry budget: every
    touch either finds, rehydrates, or serves transiently; eviction
    mid-touch skips busy victims (try_hold) — no deadlock, and the
    gauges drain to 0 afterwards."""
    cache = serving.ResidentSnapshotCache(max_bytes=0)
    ea = cache.admit(_tiny_snapshot(2, "a"))
    eb = cache.admit(_tiny_snapshot(3, "b"))
    cache.max_bytes = 10**9
    cache.device_arrays(ea)
    cache.max_bytes = int(ea.device_bytes * 1.5)
    errors = []

    def hammer(entry):
        try:
            for _ in range(25):
                dev = cache.device_arrays(entry)
                assert dev is not None
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(e,), daemon=True)
               for e in (ea, eb, ea, eb, ea, eb)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)
    assert not any(t.is_alive() for t in threads), "eviction deadlocked"
    assert not errors, errors
    cache.drop_all()
    assert telemetry.gauge("simon_resident_bytes").value() == 0
    assert telemetry.gauge("simon_resident_snapshots").value() == 0
    assert telemetry.gauge("simon_resident_entries").value() == 0


# ---- queue accounting / workers (resilience/lifecycle.py) ----------------


def test_retry_after_counts_coalesced_members():
    """The EWMA records launch-time / members and in-flight counts
    MEMBERS: a merged launch of 4 callers must not look like one fast
    job to the Retry-After estimate (the regression: 429 hints went
    k-fold optimistic under coalescing)."""
    q = lifecycle.AdmissionQueue(depth=16, initial_service_s=0.05)
    release = threading.Event()
    seen = {}

    def blocker_fn():
        release.wait(10.0)
        return "ok"

    blocker = q.submit(blocker_fn, label="blocker")

    def group_fn(jobs):
        seen["in_flight"] = q.stats()["in_flight"]
        time.sleep(0.4)
        for j in jobs:
            j.result = (200, {})

    jobs = [q.submit(None, label=f"m{i}", group_key=("d", "lanes"),
                     group_fn=group_fn) for i in range(4)]
    release.set()
    assert blocker.wait(10.0)
    for j in jobs:
        assert j.wait(10.0)
        assert j.error is None and j.result == (200, {})
    assert seen["in_flight"] == 4            # members, not launches
    # per-member service: 0.4s/4 -> ewma = 0.2*0.1 + 0.8*prior(<=0.05ish)
    # vs the regression's 0.2*0.4 + ... >= 0.105
    assert q.stats()["ewma_service_s"] < 0.1, q.stats()
    h = telemetry.REGISTRY.histogram("simon_queue_coalesce_members")
    count, total = h.child_stats()
    assert count >= 1 and total >= 4         # one launch of 4 members


def test_group_pop_only_merges_same_key():
    """Different keys never share a launch; None keys never group."""
    q = lifecycle.AdmissionQueue(depth=16)
    release = threading.Event()
    launches = []

    def group_fn(jobs):
        launches.append(sorted(j.label for j in jobs))
        for j in jobs:
            j.result = "ok"

    b = q.submit(lambda: release.wait(10.0), label="blocker")
    jobs = [
        q.submit(None, label="a1", group_key="A", group_fn=group_fn),
        q.submit(None, label="a2", group_key="A", group_fn=group_fn),
        q.submit(None, label="b1", group_key="B", group_fn=group_fn),
        q.submit(None, label="n1", group_key=None, group_fn=group_fn),
        q.submit(None, label="a3", group_key="A", group_fn=group_fn),
    ]
    release.set()
    for j in [b] + jobs:
        assert j.wait(10.0)
    assert ["a1", "a2", "a3"] in launches    # one merged A launch
    assert ["b1"] in launches and ["n1"] in launches
    assert len(launches) == 3


def test_crashed_worker_replaced_without_losing_jobs():
    """A crash of the worker LOOP (not a job) spawns a replacement that
    drains the jobs already queued."""
    q = lifecycle.AdmissionQueue(depth=16)

    def boom():
        raise MemoryError("injected worker crash")

    # prime the worker so the crash hits an already-running loop with
    # jobs waiting behind it
    first = q.submit(lambda: "warm", label="warm")
    assert first.wait(10.0) and first.result == "warm"
    q._fault_hook = boom
    jobs = [q.submit(lambda i=i: i, label=f"j{i}") for i in range(3)]
    for i, j in enumerate(jobs):
        assert j.wait(10.0), "queued job lost to the worker crash"
        assert j.error is None and j.result == i
    assert q.stats()["workers"] == 1         # the corpse was replaced


def test_multi_worker_short_jobs_pass_long_ones():
    """--workers 2: a deadline-sensitive singleton is not starved by a
    long-running job occupying the other worker."""
    q = lifecycle.AdmissionQueue(depth=16, workers=2)
    release = threading.Event()
    order = []
    long_job = q.submit(
        lambda: (release.wait(10.0), order.append("long"))[1],
        label="long")
    time.sleep(0.05)
    short = q.submit(lambda: order.append("short"), label="short")
    assert short.wait(5.0), "short job starved behind the long one"
    assert order == ["short"]
    release.set()
    assert long_job.wait(5.0)
    assert q.stats()["workers"] == 2


def test_drain_drops_resident_snapshots():
    srv, httpd, url = _mini_server()
    try:
        s, out = _post(url + "/api/simulate",
                       {"cluster": {"yaml": CLUSTER_YAML}})
        assert s == 200
        assert srv._snapshots.stats()["entries"] == 1
        info = srv.begin_drain()
        assert info["draining"] is True
        assert srv._snapshots.stats()["entries"] == 0
        assert telemetry.gauge("simon_resident_bytes").value() == 0
        s2, body = _post(url + "/api/simulate",
                         {"base": out["snapshot_digest"]})
        assert s2 == 503 and body["code"] == "E_BUSY"
    finally:
        httpd.shutdown()


# ---- capacity-specific serving paths -------------------------------------


def test_capacity_base_respects_encoded_slots(box):
    """A base digest encoded with 2 template slots serves capacity
    questions up to 2; asking for more is a structured 400 naming the
    re-POST remedy."""
    _, url = box
    body = {"cluster": {"yaml": CLUSTER_YAML},
            "new_node": {"spec_yaml": NODE_SPEC_YAML}, "max_new_nodes": 2}
    s, out = _post(url + "/api/capacity", {**body,
                                           "sweep_mode": "exhaustive"})
    assert s == 200, out
    assert out["counts"] == [0, 1, 2]
    assert len(out["lane_digests"]) == 3
    s2, more = _post(url + "/api/capacity",
                     {"base": out["snapshot_digest"], "max_new_nodes": 5,
                      "sweep_mode": "exhaustive"})
    assert s2 == 400 and more["field"] == "max_new_nodes"
    s3, same = _post(url + "/api/capacity",
                     {"base": out["snapshot_digest"],
                      "sweep_mode": "exhaustive"})
    assert s3 == 200
    assert same["digest"] == out["digest"]   # resident replay, same sweep


def test_capacity_delta_requires_exhaustive(box, base_digest):
    _, url = box
    s, out = _post(url + "/api/capacity",
                   {"base": base_digest,
                    "delta": {"remove_nodes": ["s1"]}})
    assert s == 400 and out["field"] == "sweep_mode"


def test_pod_delta_runs_singleton_but_reuses_executable(box, base_digest):
    """A forced-column overlay (pod delta) must NOT coalesce with base
    probes (different data question) — but it reuses the same cached
    executable: zero new compiles after the base probe warmed it."""
    srv, url = box
    s, warm = _post(url + "/api/simulate", {"base": base_digest})
    assert s == 200
    misses0 = telemetry.counter("simon_compile_cache_total", labelnames=("fn", "event")).value(
        fn="serving_lanes", event="miss")
    s2, out = _post(url + "/api/simulate",
                    {"base": base_digest,
                     "delta": {"remove_pods": ["default/existing-1"]}})
    assert s2 == 200, out
    assert out["coalesced_members"] == 1
    misses1 = telemetry.counter("simon_compile_cache_total", labelnames=("fn", "event")).value(
        fn="serving_lanes", event="miss")
    assert misses1 == misses0, "pod-delta overlay recompiled"


# ---- review-hardening regressions ----------------------------------------


PINNED_POD_YAML = textwrap.dedent("""
    apiVersion: v1
    kind: Pod
    metadata: {name: pinned-0, namespace: default, labels: {app: pinned}}
    spec:
      nodeName: s2
      containers:
        - name: c
          image: registry.local/p:1
          resources: {requests: {cpu: "1", memory: 1Gi}}
""")


def test_delta_remove_pinned_node_audits_clean(box):
    """Removing a node a pod is BOUND to, with audit:true, must 200:
    the auditor gets the overlay forced column (pin rewritten to
    NODE_GONE -> free), not the base pin — auditing against the base
    would flag the valid delta itself as a forced-bind violation."""
    _, url = box
    yaml_text = CLUSTER_YAML + "\n---\n" + PINNED_POD_YAML
    s, out = _post(url + "/api/simulate", {"cluster": {"yaml": yaml_text}})
    assert s == 200, out
    s1, hot = _post(url + "/api/simulate",
                    {"base": out["snapshot_digest"],
                     "delta": {"remove_nodes": ["s2"]}, "audit": True})
    assert s1 == 200, hot
    # and the overlay still digests like a cold re-encode of the shrunk
    # cluster (the pinned pod keeps nodeName: s2 -> "node not found")
    cold_yaml = "\n---\n".join(
        doc for doc in yaml_text.split("---")
        if not ("kind: Node" in doc and "name: s2" in doc))
    s2c, cold = _post(url + "/api/simulate", {"cluster": {"yaml": cold_yaml}})
    assert s2c == 200, cold
    assert hot["digest"] == cold["digest"]
    assert hot["placed"] == cold["placed"]


def test_rejected_fullbody_delta_never_admits():
    """A full-body request whose delta is rejected must not admit its
    snapshot: admission after a 400 would churn another client's entry
    out of the bounded LRU table."""
    srv, httpd, url = _mini_server()
    try:
        s, _ = _post(url + "/api/simulate", {"cluster": {"yaml": CLUSTER_YAML}})
        assert s == 200
        before = _cache_state(srv)
        smaller = CLUSTER_YAML.replace("replicas: 4", "replicas: 2")
        s1, body = _post(url + "/api/simulate",
                         {"cluster": {"yaml": smaller},
                          "delta": {"remove_nodes": ["ghost"]}})
        assert s1 == 400 and body["field"] == "delta.remove_nodes"
        assert _cache_state(srv) == before
        # full-body bisect + delta rejects before resolving, too
        s2, body2 = _post(url + "/api/capacity",
                          {"cluster": {"yaml": smaller},
                           "new_node": {"spec_yaml": NODE_SPEC_YAML},
                           "delta": {"add_nodes": 1}})
        assert s2 == 400 and body2["field"] == "sweep_mode"
        assert _cache_state(srv) == before
    finally:
        httpd.shutdown()


class _FakeJob:
    """The slice of lifecycle.Job the group executor reads."""

    def __init__(self, payload):
        self.payload = payload
        self.token = None
        self.result = None
        self.error = None


def test_lane_bucketing_bounds_compiles(box, base_digest):
    """Coalesced group sizes vary with queue timing; the launch pads the
    lane axis to a power-of-two bucket so a 3-member and a 4-member
    group share ONE executable instead of compiling per size."""
    srv, _ = box
    cc = telemetry.counter("simon_compile_cache_total",
                           labelnames=("fn", "event"))

    def group(n):
        return [_FakeJob(serving.prepare_simulate(srv, {"base": base_digest}))
                for _ in range(n)]

    g3 = group(3)
    serving.execute_group(g3)                  # buckets to 4 lanes
    m0 = cc.value(fn="serving_lanes", event="miss")
    g4 = group(4)
    serving.execute_group(g4)                  # same bucket: cache hit
    m1 = cc.value(fn="serving_lanes", event="miss")
    assert m1 == m0, "group sizes 3 and 4 compiled separately"
    assert all(j.result[0] == 200 for j in g3 + g4)
    digests = {j.result[1]["digest"] for j in g3 + g4}
    assert len(digests) == 1                   # filler lanes never decoded


def test_launch_failure_answers_structured(box, base_digest):
    """A SimulationError out of the whole launch (retries exhausted,
    rehydration failure) must reach every member as its STRUCTURED
    code/status, not an opaque 500."""
    srv, url = box
    real = serving.ResidentSnapshotCache.device_arrays

    def boom(self, entry):
        raise SimulationError("injected transfer failure",
                              code="E_TIMEOUT", ref="test",
                              hint="try again")

    serving.ResidentSnapshotCache.device_arrays = boom
    try:
        s, body = _post(url + "/api/simulate", {"base": base_digest})
    finally:
        serving.ResidentSnapshotCache.device_arrays = real
    assert s == 504, body
    assert body["code"] == "E_TIMEOUT" and body["hint"] == "try again"
