"""CLI + applier end-to-end over the examples corpus."""

import os
import textwrap

from open_simulator_tpu.cli.main import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_version(capsys):
    assert main(["version"]) == 0
    assert "simon-tpu version" in capsys.readouterr().out


def test_apply_demo_fits(capsys):
    rc = main(["apply", "-f", os.path.join(REPO, "examples/config.yaml"), "--max-new-nodes", "4"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no new nodes needed" in out
    assert "=== Cluster ===" in out
    assert "orders-db-2" in out


def test_apply_needs_new_nodes(tmp_path, capsys):
    # Undersized cluster: one small worker; app wants 4 big pods.
    cluster = tmp_path / "cluster"
    cluster.mkdir()
    (cluster / "node.yaml").write_text(textwrap.dedent("""
        apiVersion: v1
        kind: Node
        metadata: {name: tiny-0}
        status:
          allocatable: {cpu: "2", memory: 4Gi, pods: "110"}
    """))
    apps = tmp_path / "apps"
    apps.mkdir()
    (apps / "big.yaml").write_text(textwrap.dedent("""
        apiVersion: apps/v1
        kind: Deployment
        metadata: {name: big, namespace: default}
        spec:
          replicas: 4
          selector: {matchLabels: {app: big}}
          template:
            metadata: {labels: {app: big}}
            spec:
              containers:
                - name: c
                  image: registry.local/big:1
                  resources: {requests: {cpu: 1500m, memory: 2Gi}}
    """))
    (tmp_path / "newnode.yaml").write_text(textwrap.dedent("""
        apiVersion: v1
        kind: Node
        metadata: {name: template}
        status:
          allocatable: {cpu: "4", memory: 8Gi, pods: "110"}
    """))
    (tmp_path / "config.yaml").write_text(textwrap.dedent("""
        apiVersion: simon/v1alpha1
        kind: Config
        metadata: {name: t}
        spec:
          cluster: {customConfig: cluster}
          appList:
            - {name: big, path: apps}
          newNode: newnode.yaml
    """))
    rc = main(["apply", "-f", str(tmp_path / "config.yaml"), "--max-new-nodes", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    # 4 pods x 1500m: tiny-0 fits 1 (2000m); each new 4-cpu node fits 2.
    # 3 remaining pods -> 2 new nodes.
    assert "requires 2 new node(s)" in out
    assert "(new)" in out


def test_apply_bad_config(tmp_path, capsys):
    (tmp_path / "bad.yaml").write_text("apiVersion: v1\nkind: Pod\n")
    rc = main(["apply", "-f", str(tmp_path / "bad.yaml")])
    assert rc == 1
    assert "expected apiVersion simon/v1alpha1" in capsys.readouterr().err


def test_output_file(tmp_path):
    out_file = tmp_path / "report.txt"
    rc = main(["apply", "-f", os.path.join(REPO, "examples/config.yaml"),
               "--max-new-nodes", "2", "--output-file", str(out_file)])
    assert rc == 0
    assert "=== Nodes ===" in out_file.read_text()


def test_gen_doc(tmp_path):
    rc = main(["gen-doc", "--dir", str(tmp_path / "docs")])
    assert rc == 0
    files = os.listdir(tmp_path / "docs")
    assert "simon-tpu.md" in files and "simon-tpu_apply.md" in files


def test_apply_more_pods_sweep_answer(capsys):
    """The more-pods scale corpus (reference example/application/more_pods
    analog): the batched sweep must land on a stable minimum node count."""
    rc = main(["apply", "-f", os.path.join(REPO, "examples/morepods-config.yaml"),
               "--max-new-nodes", "16"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "requires 3 new node(s)" in out
