"""Golden regression tests over the examples corpus.

Placements are deterministic (lowest-index tie-break, fixed pod order), so
any engine/encoder change that shifts a placement fails here loudly. If a
change is *intended* (e.g. a scoring-parity fix), regenerate with:

    python -m tests.test_golden_examples   # rewrites tests/golden/*.json
"""

import json
import os

from open_simulator_tpu.api.v1alpha1 import load_config
from open_simulator_tpu.apply.applier import build_apps_from_config, build_cluster_from_config
from open_simulator_tpu.core import simulate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(REPO, "tests", "golden")


def _run_config(config_name):
    cfg = load_config(os.path.join(REPO, "examples", config_name))
    base = os.path.join(REPO, "examples")
    cluster = build_cluster_from_config(cfg, base)
    apps = build_apps_from_config(cfg, base)
    result = simulate(cluster, apps)
    return {
        "placements": dict(sorted(result.placements().items())),
        "unscheduled": sorted(u.pod.key for u in result.unscheduled_pods),
    }


CONFIGS = ["config.yaml", "gpushare-config.yaml", "openlocal-config.yaml", "stateful-config.yaml", "chart-config.yaml", "morepods-config.yaml", "constraints-config.yaml", "controlplane-config.yaml"]


def _golden_path(name):
    return os.path.join(GOLDEN_DIR, name.replace(".yaml", ".json"))


def test_golden_placements():
    for name in CONFIGS:
        got = _run_config(name)
        path = _golden_path(name)
        assert os.path.exists(path), f"golden file missing — regenerate: python -m tests.test_golden_examples"
        with open(path) as f:
            want = json.load(f)
        assert got == want, f"placements changed for {name} (regenerate if intended)"


if __name__ == "__main__":
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    for name in CONFIGS:
        with open(_golden_path(name), "w") as f:
            json.dump(_run_config(name), f, indent=1, sort_keys=True)
        print("wrote", _golden_path(name))
