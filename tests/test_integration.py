"""Full-engine integration test with an invariant-checking oracle.

Modeled on the reference's single integration test
(pkg/simulator/core_test.go:31-319 TestSimulate + checkResult:321-548):
build a 4-node cluster (3 workers + 1 tainted master) with kube-system
workloads, deploy an app exercising every workload kind plus taints,
selectors, affinity, anti-affinity and spread, then independently recount
what must be true of the placement — including re-deriving DaemonSet
eligibility per node — and require zero unscheduled pods.
"""

from collections import Counter

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.k8s.objects import DaemonSet, Deployment, Job, StatefulSet
from open_simulator_tpu.models.expand import daemonset_node_should_run
from tests.conftest import make_node, make_pod


MASTER_TAINT = {"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}
MASTER_TOL = {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}


def build_cluster():
    cluster = ClusterResources()
    cluster.nodes = [
        make_node("master-0", cpu_m=8000, mem_mib=16384,
                  labels={"node-role.kubernetes.io/master": "", "zone": "z0"},
                  taints=[MASTER_TAINT]),
        make_node("worker-0", cpu_m=8000, mem_mib=16384, labels={"zone": "z0", "disk": "ssd"}),
        make_node("worker-1", cpu_m=8000, mem_mib=16384, labels={"zone": "z1"}),
        make_node("worker-2", cpu_m=8000, mem_mib=16384, labels={"zone": "z1"}),
    ]
    # kube-system daemonset runs everywhere incl. master
    cluster.daemon_sets = [DaemonSet.from_dict({
        "metadata": {"name": "proxy", "namespace": "kube-system"},
        "spec": {"selector": {"matchLabels": {"k": "proxy"}},
                 "template": {"metadata": {"labels": {"k": "proxy"}},
                              "spec": {"tolerations": [MASTER_TOL],
                                       "containers": [{"name": "p", "image": "i",
                                                       "resources": {"requests": {"cpu": "100m", "memory": "64Mi"}}}]}}},
    })]
    cluster.deployments = [Deployment.from_dict({
        "metadata": {"name": "metrics", "namespace": "kube-system"},
        "spec": {"replicas": 2, "selector": {"matchLabels": {"k": "metrics"}},
                 "template": {"metadata": {"labels": {"k": "metrics"}},
                              "spec": {"containers": [{"name": "m", "image": "i",
                                                       "resources": {"requests": {"cpu": "200m", "memory": "256Mi"}}}]}}},
    })]
    return cluster


def build_app():
    app = ClusterResources()
    app.deployments = [Deployment.from_dict({
        "metadata": {"name": "api", "namespace": "prod"},
        "spec": {"replicas": 4, "selector": {"matchLabels": {"app": "api"}},
                 "template": {"metadata": {"labels": {"app": "api"}},
                              "spec": {
                                  "topologySpreadConstraints": [{
                                      "maxSkew": 1, "topologyKey": "zone",
                                      "whenUnsatisfiable": "DoNotSchedule",
                                      "labelSelector": {"matchLabels": {"app": "api"}}}],
                                  "containers": [{"name": "c", "image": "i",
                                                  "resources": {"requests": {"cpu": "500m", "memory": "512Mi"}}}]}}},
    })]
    app.stateful_sets = [StatefulSet.from_dict({
        "metadata": {"name": "kv", "namespace": "prod"},
        "spec": {"replicas": 3, "selector": {"matchLabels": {"app": "kv"}},
                 "template": {"metadata": {"labels": {"app": "kv"}},
                              "spec": {
                                  "affinity": {"podAntiAffinity": {
                                      "requiredDuringSchedulingIgnoredDuringExecution": [{
                                          "labelSelector": {"matchLabels": {"app": "kv"}},
                                          "topologyKey": "kubernetes.io/hostname"}]}},
                                  "containers": [{"name": "c", "image": "i",
                                                  "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}]}}},
    })]
    app.daemon_sets = [DaemonSet.from_dict({
        # workers only (master not tolerated)
        "metadata": {"name": "logship", "namespace": "prod"},
        "spec": {"selector": {"matchLabels": {"app": "logship"}},
                 "template": {"metadata": {"labels": {"app": "logship"}},
                              "spec": {"containers": [{"name": "c", "image": "i",
                                                       "resources": {"requests": {"cpu": "100m", "memory": "128Mi"}}}]}}},
    })]
    app.jobs = [Job.from_dict({
        "metadata": {"name": "migrate", "namespace": "prod"},
        "spec": {"completions": 2,
                 "template": {"spec": {"containers": [{"name": "c", "image": "i",
                                                       "resources": {"requests": {"cpu": "250m", "memory": "256Mi"}}}],
                              "restartPolicy": "Never"}}},
    })]
    app.pods = [
        make_pod("pinned-tool", ns="prod", cpu="100m", mem="128Mi",
                 node_selector={"disk": "ssd"}),
        make_pod("on-master", ns="prod", cpu="100m", mem="128Mi",
                 tolerations=[MASTER_TOL],
                 node_selector={"node-role.kubernetes.io/master": ""}),
    ]
    return app


def test_full_integration_invariants():
    cluster = build_cluster()
    app = build_app()
    result = simulate(cluster, [AppResource(name="prod-app", resources=app)])

    # Oracle 0: nothing unscheduled (core_test.go expects failedPodsNum == 0)
    assert not result.unscheduled_pods, [
        (u.pod.key, u.reason) for u in result.unscheduled_pods
    ]

    placements = result.placements()
    nodes_by_name = {n.name: n for n in cluster.nodes}

    def pods_of(prefix, ns):
        return {k: v for k, v in placements.items() if k.startswith(f"{ns}/{prefix}")}

    # Oracle 1: DaemonSet eligibility independently re-derived per node
    for ds, ns in ((cluster.daemon_sets[0], "kube-system"), (app.daemon_sets[0], "prod")):
        expected_nodes = {
            n.name for n in cluster.nodes if daemonset_node_should_run(ds, n)
        }
        actual_nodes = set(pods_of(ds.meta.name, ns).values())
        assert actual_nodes == expected_nodes, (ds.meta.name, actual_nodes, expected_nodes)
    # the prod daemonset must not land on the tainted master
    assert "master-0" not in set(pods_of("logship", "prod").values())

    # Oracle 2: replica counts
    assert len(pods_of("api", "prod")) == 4
    assert len(pods_of("kv", "prod")) == 3
    assert len(pods_of("migrate", "prod")) == 2
    assert len(pods_of("metrics", "kube-system")) == 2

    # Oracle 3: anti-affinity — kv pods on 3 distinct nodes, never master
    kv_nodes = list(pods_of("kv", "prod").values())
    assert len(set(kv_nodes)) == 3 and "master-0" not in kv_nodes

    # Oracle 4: hard spread maxSkew=1 on zone for api pods (z0 has 1
    # schedulable worker, z1 has 2; master's zone counts only via its
    # schedulability — it is tainted, so zones are z0:{worker-0}, z1:{worker-1,2})
    zone_of = {n.name: n.meta.labels.get("zone") for n in cluster.nodes}
    api_zones = Counter(zone_of[v] for v in pods_of("api", "prod").values())
    assert abs(api_zones.get("z0", 0) - api_zones.get("z1", 0)) <= 1

    # Oracle 5: selectors — pinned-tool on the ssd worker, on-master on master
    assert placements["prod/pinned-tool"] == "worker-0"
    assert placements["prod/on-master"] == "master-0"

    # Oracle 6: no node over-packed on any resource
    for ns_status in result.node_status:
        alloc = ns_status.node.allocatable
        totals = Counter()
        for p in ns_status.pods:
            for r, v in p.requests().items():
                totals[r] += v
        for r, used in totals.items():
            assert used <= alloc.get(r, 0), (ns_status.node.name, r, used)

    # Oracle 7: only master-tolerating pods on the master
    for key, node in placements.items():
        if node == "master-0":
            assert key in ("prod/on-master",) or key.startswith("kube-system/proxy")
