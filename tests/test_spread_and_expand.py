"""Topology-spread hard constraints + workload expansion behaviors."""

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.k8s.objects import CronJob, Deployment, StatefulSet
from open_simulator_tpu.models.expand import expand_workload
from tests.conftest import make_node, make_pod


def run(nodes, pods):
    cluster = ClusterResources()
    cluster.nodes = list(nodes)
    app = ClusterResources()
    app.pods = list(pods)
    return simulate(cluster, [AppResource(name="app", resources=app)])


SPREAD = [{
    "maxSkew": 1,
    "topologyKey": "zone",
    "whenUnsatisfiable": "DoNotSchedule",
    "labelSelector": {"matchLabels": {"app": "web"}},
}]


def test_hard_spread_balances_zones():
    nodes = [
        make_node("a0", labels={"zone": "a"}),
        make_node("a1", labels={"zone": "a"}),
        make_node("b0", labels={"zone": "b"}),
    ]
    pods = [make_pod(f"w{i}", labels={"app": "web"}, spread=SPREAD) for i in range(6)]
    res = run(nodes, pods)
    assert not res.unscheduled_pods
    zones = {"a": 0, "b": 0}
    for sp in res.scheduled_pods:
        zones[sp.node_name[0]] += 1
    assert abs(zones["a"] - zones["b"]) <= 1


def test_hard_spread_blocks_when_zone_missing_capacity():
    # zone b full -> skew would exceed 1 -> pods become unschedulable rather
    # than piling into zone a (DoNotSchedule semantics)
    nodes = [
        make_node("a0", labels={"zone": "a"}),
        make_node("b0", cpu_m=700, labels={"zone": "b"}),  # fits 1 web pod only
    ]
    pods = [make_pod(f"w{i}", cpu="600m", labels={"app": "web"}, spread=SPREAD) for i in range(6)]
    res = run(nodes, pods)
    # w0->a or b, w1->other, w2 -> needs zone with min count... zone b capacity
    # exhausts after 1; once skew limit hits, the rest fail.
    assert 0 < len(res.unscheduled_pods)
    assert any("topology spread" in u.reason for u in res.unscheduled_pods)
    # at most min+maxSkew in zone a: b has 1 -> a gets at most 2
    a_count = sum(1 for sp in res.scheduled_pods if sp.node_name == "a0")
    assert a_count <= 2


def test_nodes_without_topology_key_fail_hard_spread():
    nodes = [make_node("nolabel")]  # no zone label
    pods = [make_pod("w0", labels={"app": "web"}, spread=SPREAD)]
    res = run(nodes, pods)
    assert len(res.unscheduled_pods) == 1
    assert "topology spread" in res.unscheduled_pods[0].reason


def test_statefulset_ordinal_names():
    sts = StatefulSet.from_dict({
        "metadata": {"name": "db", "namespace": "x"},
        "spec": {"replicas": 3, "selector": {"matchLabels": {"a": "b"}},
                 "template": {"metadata": {"labels": {"a": "b"}},
                              "spec": {"containers": [{"name": "c", "image": "i"}]}}},
    })
    pods = expand_workload(sts)
    assert [p.meta.name for p in pods] == ["db-0", "db-1", "db-2"]
    assert all(p.meta.owner_kind == "StatefulSet" for p in pods)


def test_cronjob_expansion():
    cj = CronJob.from_dict({
        "metadata": {"name": "tick", "namespace": "x"},
        "spec": {"schedule": "* * * * *",
                 "jobTemplate": {"spec": {"completions": 2,
                                          "template": {"spec": {"containers": [{"name": "c", "image": "i"}]}}}}},
    })
    pods = expand_workload(cj)
    assert len(pods) == 2
    assert pods[0].meta.owner_kind == "CronJob"


def test_zero_replica_deployment():
    d = Deployment.from_dict({
        "metadata": {"name": "off", "namespace": "x"},
        "spec": {"replicas": 0, "selector": {"matchLabels": {"a": "b"}},
                 "template": {"spec": {"containers": [{"name": "c", "image": "i"}]}}},
    })
    assert expand_workload(d) == []


def test_expanded_pod_affinity_terms_scope_to_workload_namespace():
    """Round-4 bug fix: workload expansion must parse the template with the
    workload's namespace already set — (anti-)affinity terms default their
    namespace scope at parse time, so late assignment left them scoped to
    'default' and silently matching nothing for non-default workloads."""
    dep = Deployment.from_dict({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "prod"},
        "spec": {"replicas": 2, "selector": {"matchLabels": {"app": "web"}},
                 "template": {"metadata": {"labels": {"app": "web"}},
                              "spec": {
                                  "affinity": {"podAntiAffinity": {
                                      "requiredDuringSchedulingIgnoredDuringExecution": [{
                                          "labelSelector": {"matchLabels": {"app": "web"}},
                                          "topologyKey": "kubernetes.io/hostname"}]}},
                                  "containers": [{"name": "c", "resources": {
                                      "requests": {"cpu": "100m"}}}]}}},
    })
    cluster = ClusterResources()
    cluster.nodes = [make_node("n0", cpu_m=8000)]
    app = ClusterResources()
    app.deployments = [dep]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    # one node, two mutually anti-affine replicas in ns prod: exactly one
    # schedules (before the fix both landed on n0)
    assert len(res.scheduled_pods) == 1
    assert len(res.unscheduled_pods) == 1
    assert "anti-affinity" in res.unscheduled_pods[0].reason
