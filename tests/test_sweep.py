"""Capacity sweep + sharded scenario batch on the 8-device virtual mesh."""

import jax
import numpy as np

from open_simulator_tpu.core import build_pod_sequence, AppResource
from open_simulator_tpu.encode.snapshot import EncodeOptions, encode_cluster
from open_simulator_tpu.engine.scheduler import make_config
from open_simulator_tpu.k8s.loader import ClusterResources, make_valid_node
from open_simulator_tpu.parallel import capacity_sweep, make_mesh, SweepThresholds
from tests.conftest import make_node, make_pod


def _snapshot(n_pods=12, pod_cpu="1500m", max_new=8):
    cluster = ClusterResources()
    cluster.nodes = [make_node("real-0", cpu_m=4000, mem_mib=8192)]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu=pod_cpu, mem="512Mi") for i in range(n_pods)]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    template = make_node("template", cpu_m=4000, mem_mib=8192)
    snap = encode_cluster(
        [make_valid_node(n) for n in cluster.nodes],
        pods,
        EncodeOptions(max_new_nodes=max_new, new_node_template=template),
    )
    return snap


def test_capacity_sweep_finds_min_count():
    snap = _snapshot()
    cfg = make_config(snap)
    plan = capacity_sweep(snap, cfg, counts=list(range(9)))
    # 12 pods x 1500m = 18000m; each node fits floor(4000/1500)=2 pods.
    # 12 pods need 6 nodes total -> 5 new nodes.
    assert plan.best_count == 5
    assert plan.all_scheduled == [c >= 5 for c in range(9)]
    # monotone: more nodes never decreases scheduled pods
    scheduled_counts = [(plan.nodes_per_scenario[s] >= 0).sum() for s in range(9)]
    assert scheduled_counts == sorted(scheduled_counts)


def test_capacity_sweep_occupancy_threshold():
    snap = _snapshot()
    cfg = make_config(snap)
    # Tight CPU occupancy cap forces more headroom than bare fit.
    plan = capacity_sweep(
        snap, cfg, counts=list(range(9)), thresholds=SweepThresholds(max_cpu_pct=60.0)
    )
    # 18000m total request; need total alloc >= 30000m -> 8 nodes -> 7 new.
    assert plan.best_count == 7


def test_sweep_on_device_mesh_matches_single_device():
    snap = _snapshot()
    cfg = make_config(snap)
    counts = list(range(8))
    mesh = make_mesh()  # 8 virtual CPU devices on the scenario axis
    assert mesh.devices.size == len(jax.devices())
    plan_mesh = capacity_sweep(snap, cfg, counts=counts, mesh=mesh)
    plan_single = capacity_sweep(snap, cfg, counts=counts)
    assert plan_mesh.best_count == plan_single.best_count
    np.testing.assert_array_equal(plan_mesh.nodes_per_scenario, plan_single.nodes_per_scenario)


def test_node_axis_sharding_bit_equal_across_meshes():
    """VERDICT r3: the node-axis sharding claim had no equality test. The
    same snapshot swept on mesh shapes 1x1, 4x2, and 2x4 (scenario x node)
    must produce bit-identical picks and fail counts — GSPMD resharding of
    the node-state arrays cannot be allowed to change a single argmax."""
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )
    import jax.numpy as jnp

    snap = _snapshot(n_pods=16, max_new=7)  # 8 total nodes: divisible by 2 and 4
    cfg = make_config(snap)
    counts = [0, 2, 4, 7] * 2               # 8 lanes
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append((np.asarray(out.node), np.asarray(out.fail_counts),
                        np.asarray(out.state.headroom)))
    base = results[0]
    for got in results[1:]:
        np.testing.assert_array_equal(got[0], base[0])
        np.testing.assert_array_equal(got[1], base[1])
        np.testing.assert_allclose(got[2], base[2], rtol=0, atol=0)


def test_node_axis_sharding_with_spread_constraints():
    """Node-sharded lanes with zone spread: the dom_count carry and hoisted
    domain stats must survive node-axis partitioning bit-for-bit."""
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )
    import jax.numpy as jnp

    cluster = ClusterResources()
    cluster.nodes = [
        make_node(f"real-{i}", cpu_m=4000, mem_mib=8192,
                  labels={"topology.kubernetes.io/zone": f"z{i % 2}"})
        for i in range(4)
    ]
    app = ClusterResources()
    spread = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "a0"}},
    }]
    app.pods = [
        make_pod(f"p{i}", cpu="900m", mem="256Mi", labels={"app": "a0"},
                 spread=spread)
        for i in range(10)
    ]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    template = make_node("template", cpu_m=4000, mem_mib=8192,
                         labels={"topology.kubernetes.io/zone": "z0"})
    snap = encode_cluster(
        [make_valid_node(n) for n in cluster.nodes], pods,
        EncodeOptions(max_new_nodes=4, new_node_template=template),
    )
    cfg = make_config(snap)
    assert cfg.enable_spread_hard
    counts = [0, 1, 2, 4]
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append(np.asarray(out.node))
    np.testing.assert_array_equal(results[1], results[0])
    np.testing.assert_array_equal(results[2], results[0])


def test_node_axis_sharding_bit_equal_all_ops():
    """Same mesh-shape equality as above, but on the all-ops workload —
    the sparse-slot column updates (dynamic-update-slice on the sharded
    carries), affinity/anti-affinity/spread ops, and ports must survive
    GSPMD resharding bit-for-bit too."""
    import __graft_entry__ as ge
    import jax.numpy as jnp
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )

    snap = ge._synthetic_snapshot(n_nodes=8, n_pods=48, max_new=8, rich=True)
    cfg = make_config(snap)
    assert cfg.slot_paint and cfg.enable_anti_affinity and cfg.enable_spread
    counts = [0, 2, 5, 8] * 2               # 8 lanes; 16 total nodes
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append((np.asarray(out.node), np.asarray(out.fail_counts),
                        np.asarray(out.state.headroom),
                        np.asarray(out.state.term_block),
                        np.asarray(out.state.group_count)))
    base = results[0]
    for got in results[1:]:
        for a, b in zip(got, base):
            np.testing.assert_array_equal(a, b)
