"""Capacity sweep + sharded scenario batch on the 8-device virtual mesh."""

import jax
import numpy as np

from open_simulator_tpu.core import build_pod_sequence, AppResource
from open_simulator_tpu.encode.snapshot import EncodeOptions, encode_cluster
from open_simulator_tpu.engine.scheduler import make_config
from open_simulator_tpu.k8s.loader import ClusterResources, make_valid_node
from open_simulator_tpu.parallel import capacity_sweep, make_mesh, SweepThresholds
from tests.conftest import make_node, make_pod


def _snapshot(n_pods=12, pod_cpu="1500m", max_new=8):
    cluster = ClusterResources()
    cluster.nodes = [make_node("real-0", cpu_m=4000, mem_mib=8192)]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu=pod_cpu, mem="512Mi") for i in range(n_pods)]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    template = make_node("template", cpu_m=4000, mem_mib=8192)
    snap = encode_cluster(
        [make_valid_node(n) for n in cluster.nodes],
        pods,
        EncodeOptions(max_new_nodes=max_new, new_node_template=template),
    )
    return snap


def test_capacity_sweep_finds_min_count():
    snap = _snapshot()
    cfg = make_config(snap)
    plan = capacity_sweep(snap, cfg, counts=list(range(9)))
    # 12 pods x 1500m = 18000m; each node fits floor(4000/1500)=2 pods.
    # 12 pods need 6 nodes total -> 5 new nodes.
    assert plan.best_count == 5
    assert plan.all_scheduled == [c >= 5 for c in range(9)]
    # monotone: more nodes never decreases scheduled pods
    scheduled_counts = [(plan.nodes_per_scenario[s] >= 0).sum() for s in range(9)]
    assert scheduled_counts == sorted(scheduled_counts)


def test_capacity_sweep_occupancy_threshold():
    snap = _snapshot()
    cfg = make_config(snap)
    # Tight CPU occupancy cap forces more headroom than bare fit.
    plan = capacity_sweep(
        snap, cfg, counts=list(range(9)), thresholds=SweepThresholds(max_cpu_pct=60.0)
    )
    # 18000m total request; need total alloc >= 30000m -> 8 nodes -> 7 new.
    assert plan.best_count == 7


def test_sweep_on_device_mesh_matches_single_device():
    snap = _snapshot()
    cfg = make_config(snap)
    counts = list(range(8))
    mesh = make_mesh()  # 8 virtual CPU devices on the scenario axis
    assert mesh.devices.size == len(jax.devices())
    plan_mesh = capacity_sweep(snap, cfg, counts=counts, mesh=mesh)
    plan_single = capacity_sweep(snap, cfg, counts=counts)
    assert plan_mesh.best_count == plan_single.best_count
    np.testing.assert_array_equal(plan_mesh.nodes_per_scenario, plan_single.nodes_per_scenario)


def test_mesh_bisect_donated_carry_digest_matches_single_device():
    """ISSUE 19: the bisection threads its donated carry through the
    CACHED mesh path — every round after the first reuses round one's
    sharded executable (`mesh_schedule` miss delta == 1 across the whole
    bisect), and the resulting plan is ledger-digest-identical to the
    single-device bisect's."""
    from open_simulator_tpu.parallel import capacity_bisect
    from open_simulator_tpu.telemetry import counter, ledger

    snap = _snapshot()
    cfg = make_config(snap)
    # 4x2: the scenario axis must divide the lane count (4 lanes below)
    mesh = make_mesh(n_scenario=4, n_node=2)

    def miss():
        return counter("simon_compile_cache_total", "",
                       labelnames=("fn", "event")).value(
                           fn="mesh_schedule", event="miss")

    # lanes=4 keys a mask shape no other mesh test compiles, so the
    # delta below counts THIS bisect's compiles only
    m0 = miss()
    plan_mesh = capacity_bisect(snap, cfg, max_new=8, mesh=mesh, lanes=4)
    assert miss() - m0 == 1
    plan_single = capacity_bisect(snap, cfg, max_new=8, lanes=4)
    assert plan_mesh.best_count == plan_single.best_count
    assert (ledger.plan_digest(plan_mesh)["digest"]
            == ledger.plan_digest(plan_single)["digest"])


def test_node_axis_sharding_bit_equal_across_meshes():
    """VERDICT r3: the node-axis sharding claim had no equality test. The
    same snapshot swept on mesh shapes 1x1, 4x2, and 2x4 (scenario x node)
    must produce bit-identical picks and fail counts — GSPMD resharding of
    the node-state arrays cannot be allowed to change a single argmax."""
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )
    import jax.numpy as jnp

    snap = _snapshot(n_pods=16, max_new=7)  # 8 total nodes: divisible by 2 and 4
    cfg = make_config(snap)
    counts = [0, 2, 4, 7] * 2               # 8 lanes
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append((np.asarray(out.node), np.asarray(out.fail_counts),
                        np.asarray(out.state.headroom)))
    base = results[0]
    for got in results[1:]:
        np.testing.assert_array_equal(got[0], base[0])
        np.testing.assert_array_equal(got[1], base[1])
        np.testing.assert_allclose(got[2], base[2], rtol=0, atol=0)


def test_node_axis_sharding_with_spread_constraints():
    """Node-sharded lanes with zone spread: the dom_count carry and hoisted
    domain stats must survive node-axis partitioning bit-for-bit."""
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )
    import jax.numpy as jnp

    cluster = ClusterResources()
    cluster.nodes = [
        make_node(f"real-{i}", cpu_m=4000, mem_mib=8192,
                  labels={"topology.kubernetes.io/zone": f"z{i % 2}"})
        for i in range(4)
    ]
    app = ClusterResources()
    spread = [{
        "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "a0"}},
    }]
    app.pods = [
        make_pod(f"p{i}", cpu="900m", mem="256Mi", labels={"app": "a0"},
                 spread=spread)
        for i in range(10)
    ]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    template = make_node("template", cpu_m=4000, mem_mib=8192,
                         labels={"topology.kubernetes.io/zone": "z0"})
    snap = encode_cluster(
        [make_valid_node(n) for n in cluster.nodes], pods,
        EncodeOptions(max_new_nodes=4, new_node_template=template),
    )
    cfg = make_config(snap)
    assert cfg.enable_spread_hard
    counts = [0, 1, 2, 4]
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append(np.asarray(out.node))
    np.testing.assert_array_equal(results[1], results[0])
    np.testing.assert_array_equal(results[2], results[0])


def test_make_mesh_require_all_rejects_partial_use():
    """require_all: multi-host callers must not silently drop a host's
    devices (a host with no addressable shard hangs instead of erroring)."""
    import pytest

    n = len(jax.devices())
    assert n == 8
    # 3x2 = 6 of 8 devices: fine by default, rejected with require_all
    mesh = make_mesh(n_scenario=3, n_node=2)
    assert mesh.devices.size == 6
    with pytest.raises(ValueError, match="uses 6 of 8 devices"):
        make_mesh(n_scenario=3, n_node=2, require_all=True)
    # an oversubscribed mesh always errors
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(n_scenario=8, n_node=2)


def test_shard_arrays_axis_placement_when_n_nodes_equals_n_pods():
    """The docstring's warning case: with n_nodes == n_pods a shape
    heuristic could shard the pod axis by accident. The declared sets
    must put node-first arrays on axis 0 and node-second on axis 1, and
    leave pod-axis arrays replicated."""
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import shard_arrays

    cluster = ClusterResources()
    cluster.nodes = [make_node(f"n{i}", cpu_m=4000, mem_mib=8192)
                     for i in range(8)]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu="100m", mem="64Mi") for i in range(8)]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    snap = encode_cluster([make_valid_node(n) for n in cluster.nodes], pods)
    assert snap.n_nodes == snap.n_pods == 8  # the ambiguous shape

    mesh = make_mesh(n_scenario=4, n_node=2)
    placed = shard_arrays(device_arrays(snap), mesh)

    def axes(x):
        return getattr(x.sharding, "spec", None)

    assert tuple(axes(placed.alloc)) == ("node", None)        # node-first
    assert tuple(axes(placed.active)) == ("node",)
    assert tuple(axes(placed.topo_onehot)) == (None, "node", None)  # node-second
    assert tuple(axes(placed.class_affinity)) == (None, "node")
    # pod-axis arrays replicated — every entry None
    assert all(s is None for s in tuple(axes(placed.req)))
    assert all(s is None for s in tuple(axes(placed.forced_node)))


def test_isolated_lane_pick_shape_mismatch_is_recorded(monkeypatch):
    """Satellite: the isolated-lane fallback used to silently keep zero
    gpu/vol picks when the lane's output width drifted from the batch
    layout; it must now record the lane in trial_errors."""
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel import sweep as sweep_mod

    snap = _snapshot(n_pods=4, pod_cpu="500m", max_new=1)
    cfg = make_config(snap)._replace(enable_gpu=True)
    n_real = snap.n_real_nodes
    real_batched = sweep_mod.batched_schedule

    def drifted(arrs, masks, cfg_, mesh=None, **kw):
        if masks.shape[0] > 1:
            raise RuntimeError("injected: force the isolated fallback")
        out = real_batched(arrs, masks, cfg_, mesh=mesh, **kw)
        if int(np.asarray(masks[0]).sum()) - n_real == 0:
            # lane for count=0: gpu_pick width drifted from the batch
            return out._replace(
                gpu_pick=np.zeros((1, np.asarray(out.node).shape[1], 99),
                                  dtype=np.int32))
        return out

    monkeypatch.setattr(sweep_mod, "batched_schedule", drifted)
    plan = sweep_mod.capacity_sweep(snap, cfg, [0, 1], backoff_s=0.0)
    assert list(plan.trial_errors) == [0]
    assert "gpu_pick shape" in plan.trial_errors[0]
    assert not plan.satisfied[0]
    assert plan.all_scheduled[1]


def test_all_lanes_failed_message_survives_any_lane_numbering(monkeypatch):
    """Satellite: the all-lanes-failed diagnostic reads SOME recorded
    error (next(iter(...))) instead of hard-indexing trial_errors[0]."""
    import pytest

    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel import sweep as sweep_mod

    snap = _snapshot(n_pods=4, pod_cpu="500m", max_new=1)
    cfg = make_config(snap)

    def dead(*a, **kw):
        raise RuntimeError("device gone")

    monkeypatch.setattr(sweep_mod, "batched_schedule", dead)
    with pytest.raises(RuntimeError,
                       match="all 2 sweep trials failed; first: .*device gone"):
        sweep_mod.capacity_sweep(snap, cfg, [0, 1], backoff_s=0.0)


def test_node_axis_sharding_bit_equal_all_ops():
    """Same mesh-shape equality as above, but on the all-ops workload —
    the sparse-slot column updates (dynamic-update-slice on the sharded
    carries), affinity/anti-affinity/spread ops, and ports must survive
    GSPMD resharding bit-for-bit too."""
    import __graft_entry__ as ge
    import jax.numpy as jnp
    from open_simulator_tpu.engine.scheduler import device_arrays
    from open_simulator_tpu.parallel.sweep import (
        active_masks_for_counts,
        batched_schedule,
        shard_arrays,
    )

    snap = ge._synthetic_snapshot(n_nodes=8, n_pods=48, max_new=8, rich=True)
    cfg = make_config(snap)
    assert cfg.slot_paint and cfg.enable_anti_affinity and cfg.enable_spread
    counts = [0, 2, 5, 8] * 2               # 8 lanes; 16 total nodes
    masks = jnp.asarray(active_masks_for_counts(snap, counts))

    results = []
    for n_scen, n_node in [(1, 1), (4, 2), (2, 4)]:
        mesh = make_mesh(n_scenario=n_scen, n_node=n_node)
        arrs = shard_arrays(device_arrays(snap), mesh)
        out = batched_schedule(arrs, masks, cfg, mesh=mesh)
        results.append((np.asarray(out.node), np.asarray(out.fail_counts),
                        np.asarray(out.state.headroom),
                        np.asarray(out.state.term_block),
                        np.asarray(out.state.group_count)))
    base = results[0]
    for got in results[1:]:
        for a, b in zip(got, base):
            np.testing.assert_array_equal(a, b)
