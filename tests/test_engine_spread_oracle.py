"""Differential oracle for the scan engine's INLINE PodTopologySpread path.

VERDICT r3: the oracle tests used to target the standalone
topology_spread_score op, which was no longer on the product path. These
tests re-derive the vendored semantics (filtering.go skew check,
scoring.go two-pass ScheduleAnyway score) in a step-by-step numpy
mini-engine and compare the scan's actual assignment sequence against it —
so the shared pass-1, the dom_count carry, the hoisted eligibility stats,
and spread_apply are all exercised on the live path.

Score isolation: w_balanced/w_least/w_simon are zeroed so the ScheduleAnyway
score is the only differentiator; ties resolve to the lowest node index in
both implementations (deterministic argmax).
"""

import numpy as np
import pytest

from open_simulator_tpu.encode.snapshot import encode_cluster
from open_simulator_tpu.engine.scheduler import (
    device_arrays,
    make_config,
    schedule_pods,
)
from tests.conftest import make_node, make_pod

ZONE_KEY = "topology.kubernetes.io/zone"


def build(n_nodes, zones, pods_spec, cpu_cap=8000):
    """pods_spec: list of (cpu_m, mode) with mode in {'soft','hard',None};
    all pods carry label app=a0 and (if mode) a zone spread over app=a0."""
    nodes = [
        make_node(f"n{i}", cpu_m=cpu_cap, mem_mib=32768,
                  labels={ZONE_KEY: f"z{zones[i]}"} if zones[i] is not None else {})
        for i in range(n_nodes)
    ]
    pods = []
    for i, (cpu_m, mode, skew) in enumerate(pods_spec):
        kw = dict(cpu=f"{cpu_m}m", mem="64Mi", labels={"app": "a0"})
        if mode:
            kw["spread"] = [{
                "maxSkew": skew, "topologyKey": ZONE_KEY,
                "whenUnsatisfiable": "DoNotSchedule" if mode == "hard" else "ScheduleAnyway",
                "labelSelector": {"matchLabels": {"app": "a0"}},
            }]
        pods.append(make_pod(f"p{i}", **kw))
    return nodes, pods


def numpy_oracle(n_nodes, zones, pods_spec, cpu_cap=8000):
    """Step-by-step mini-engine: fit + zone spread filter/score only."""
    zone_ids = sorted({z for z in zones if z is not None})
    zmap = {z: k for k, z in enumerate(zone_ids)}
    node_zone = [zmap[z] if z is not None else -1 for z in zones]
    has_key = np.array([z >= 0 for z in node_zone])
    n_domains = len(zone_ids)
    log_w = np.log(n_domains + 2.0)

    cpu_used = np.zeros(n_nodes)
    match_count = np.zeros(n_nodes)          # bound app=a0 pods per node
    zone_count = np.zeros(max(n_domains, 1))
    assign = []
    for (cpu_m, mode, skew) in pods_spec:
        fit = cpu_used + cpu_m <= cpu_cap
        dc = np.array([zone_count[node_zone[n]] if node_zone[n] >= 0 else 0.0
                       for n in range(n_nodes)])
        ok = fit.copy()
        if mode == "hard":
            # min over domains holding an eligible node; all nodes eligible
            elig_domains = {node_zone[n] for n in range(n_nodes) if node_zone[n] >= 0}
            min_val = min(zone_count[d] for d in elig_domains) if elig_domains else 0.0
            self_m = 1.0  # every pod matches its own selector here
            ok &= has_key & (dc + self_m - min_val <= skew)
        # score: ScheduleAnyway two-pass over feasible nodes
        if mode == "soft":
            raw = dc * log_w + (skew - 1.0)
            scored = ok & has_key
            if scored.any():
                mx, mn = raw[scored].max(), raw[scored].min()
                sc = (100.0 * (mx + mn - raw) / max(mx, 1e-9)
                      if mx > 0 else np.full(n_nodes, 100.0))
            else:
                sc = np.zeros(n_nodes)
            score = np.where(scored, sc, 0.0)
        else:
            score = np.zeros(n_nodes)
        if not ok.any():
            assign.append(-1)
            continue
        pick = int(np.argmax(np.where(ok, score, -np.inf)))
        assign.append(pick)
        cpu_used[pick] += cpu_m
        match_count[pick] += 1
        if node_zone[pick] >= 0:
            zone_count[node_zone[pick]] += 1
    return np.array(assign)


def run_engine(nodes, pods):
    snap = encode_cluster(nodes, pods)
    cfg = make_config(snap, w_balanced=0.0, w_least=0.0, w_simon=0.0)
    out = schedule_pods(device_arrays(snap), snap.arrays.active, cfg)
    return np.asarray(out.node)


@pytest.mark.parametrize("seed", range(4))
def test_soft_spread_assignment_sequence_matches_oracle(seed):
    rng = np.random.RandomState(seed)
    n = 9
    zones = [i % 3 for i in range(n)]
    spec = [(int(rng.randint(100, 600)), "soft", int(rng.randint(1, 4)))
            for _ in range(40)]
    nodes, pods = build(n, zones, spec)
    np.testing.assert_array_equal(run_engine(nodes, pods),
                                  numpy_oracle(n, zones, spec))


@pytest.mark.parametrize("seed", range(4))
def test_hard_spread_assignment_sequence_matches_oracle(seed):
    rng = np.random.RandomState(seed + 50)
    n = 6
    zones = [i % 3 for i in range(n)]
    spec = [(int(rng.randint(100, 500)), "hard", 1) for _ in range(30)]
    nodes, pods = build(n, zones, spec)
    np.testing.assert_array_equal(run_engine(nodes, pods),
                                  numpy_oracle(n, zones, spec))


def test_hard_spread_blocks_when_min_zone_is_full():
    """The vendored skew semantics: when the min-count zone has no capacity
    left, DoNotSchedule pods cannot overflow into other zones beyond
    maxSkew — they go unschedulable even though cpu is free elsewhere."""
    # zone 0 tiny (fills after 2 pods), zones 1/2 huge
    zones = [0, 1, 2]
    spec = [(1000, "hard", 1) for _ in range(8)]
    nodes, pods = build(3, zones, spec, cpu_cap=2000)
    # z0's node holds 2 x 1000m; z1/z2 can hold 2 each before skew blocks
    got = run_engine(nodes, pods)
    want = numpy_oracle(3, zones, spec, cpu_cap=2000)
    np.testing.assert_array_equal(got, want)
    assert (got == -1).sum() > 0  # the block actually happened
    assert (got >= 0).sum() == 6


def test_mixed_soft_hard_sequence_matches_oracle():
    rng = np.random.RandomState(9)
    n = 9
    zones = [i % 3 for i in range(n)]
    spec = []
    for i in range(36):
        mode = ("hard", "soft", None)[i % 3]
        spec.append((int(rng.randint(100, 400)), mode, int(rng.randint(1, 3))))
    nodes, pods = build(n, zones, spec)
    np.testing.assert_array_equal(run_engine(nodes, pods),
                                  numpy_oracle(n, zones, spec))


def test_nodes_missing_zone_key_score_zero_and_fail_hard():
    """IgnoredNodes parity: a node without the topology key scores 0 for
    soft constraints (never preferred) and fails DoNotSchedule outright."""
    zones = [0, 1, None]
    # soft pods: keyless node must lose to any keyed node despite emptiness
    spec = [(100, "soft", 1) for _ in range(4)]
    nodes, pods = build(3, zones, spec)
    got = run_engine(nodes, pods)
    np.testing.assert_array_equal(got, numpy_oracle(3, zones, spec))
    assert 2 not in got[:2]  # keyed nodes preferred while feasible
    # hard pods: keyless node is infeasible
    spec_h = [(100, "hard", 1) for _ in range(4)]
    nodes, pods = build(3, zones, spec_h)
    got_h = run_engine(nodes, pods)
    np.testing.assert_array_equal(got_h, numpy_oracle(3, zones, spec_h))
    assert 2 not in got_h
