"""Incremental Simulator session API: prefix stability across apps."""

from open_simulator_tpu.core import AppResource
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.simulator import Simulator
from open_simulator_tpu.testing import make_fake_deployment, make_fake_node, make_fake_pod


def test_incremental_apps_keep_prior_placements():
    cluster = ClusterResources()
    cluster.nodes = [make_fake_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    cluster.pods = [make_fake_pod("seed", node_name="n0", cpu="1")]

    sim = Simulator(cluster)
    r0 = sim.run_cluster()
    assert r0.placements() == {"default/seed": "n0"}

    app1 = ClusterResources()
    app1.deployments = [make_fake_deployment("alpha", replicas=3, cpu="2")]
    r1 = sim.schedule_app(AppResource(name="alpha", resources=app1))
    assert len(r1.scheduled_pods) == 3
    alpha_placements = {s.pod.key: s.node_name for s in r1.scheduled_pods}

    app2 = ClusterResources()
    app2.deployments = [make_fake_deployment("beta", replicas=2, cpu="2")]
    r2 = sim.schedule_app(AppResource(name="beta", resources=app2))
    # beta's result contains only beta pods
    assert all("beta" in s.pod.meta.name for s in r2.scheduled_pods)
    # alpha's placements are unchanged in the full state view
    full = sim.cluster_status().placements()
    for key, node in alpha_placements.items():
        assert full[key] == node
    assert full["default/seed"] == "n0"
    sim.close()


def test_app_overflow_reported_per_app():
    cluster = ClusterResources()
    cluster.nodes = [make_fake_node("n0", cpu="2")]
    sim = Simulator(cluster)
    sim.run_cluster()
    app = ClusterResources()
    app.deployments = [make_fake_deployment("big", replicas=3, cpu="1")]
    r = sim.schedule_app(AppResource(name="big", resources=app))
    assert len(r.scheduled_pods) == 2  # 2000m / 1000m
    assert len(r.unscheduled_pods) == 1
    assert "Insufficient cpu" in r.unscheduled_pods[0].reason
