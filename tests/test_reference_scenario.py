"""1:1 port of the reference's own integration scenario.

Reproduces pkg/simulator/core_test.go:31-319 TestSimulate "simple"
faithfully — the same 3 masters + 1 worker (8 cpu / 16Gi), the same
kube-system static pods / metrics-server / kube-proxy / coredns, and the
same "simple" app (busybox deploy/DS/job/pod/STS + calico RS with taints,
node affinity, anti-affinity on a zone key no node carries, and preferred
hostname anti-affinity) — then asserts the checkResult invariants
(core_test.go:321-548): zero unscheduled pods and an independent
per-workload recount of expected pods, with DaemonSet placement re-derived
per node via the daemon-controller predicates.
"""

from collections import Counter

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.models.expand import daemonset_node_should_run
from open_simulator_tpu.testing.builders import (
    make_fake_daemonset,
    make_fake_deployment,
    make_fake_job,
    make_fake_node,
    make_fake_pod,
    make_fake_replicaset,
    make_fake_statefulset,
)

MASTER_LABELS = {
    "beta.kubernetes.io/arch": "amd64",
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/arch": "amd64",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/master": "",
}
WORKER_LABELS = {
    "beta.kubernetes.io/arch": "amd64",
    "beta.kubernetes.io/os": "linux",
    "kubernetes.io/arch": "amd64",
    "kubernetes.io/os": "linux",
    "node-role.kubernetes.io/worker": "",
}
MASTER_EXISTS = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
    "nodeSelectorTerms": [{"matchExpressions": [
        {"key": "node-role.kubernetes.io/master", "operator": "Exists"}]}]}}}


def _node(name, labels, taints=None):
    return make_fake_node(name, cpu="8", memory="16Gi",
                          labels={**labels, "kubernetes.io/hostname": name},
                          taints=taints)


def build_cluster() -> ClusterResources:
    cluster = ClusterResources()
    cluster.nodes = [
        _node("master-1", MASTER_LABELS,
              taints=[{"key": "node-role.kubernetes.io/master",
                       "effect": "NoSchedule"}]),
        _node("master-2", MASTER_LABELS),
        _node("master-3", MASTER_LABELS),
        _node("worker-1", WORKER_LABELS),
    ]
    # static control-plane pods pinned to master-1 (MakeFakePod + nodeName)
    cluster.pods = [
        make_fake_pod("etcd-master-1", "kube-system", cpu="0", memory="0",
                      node_name="master-1"),
        make_fake_pod("kube-apiserver-master-1", "kube-system", cpu="250m",
                      memory="0", node_name="master-1"),
        make_fake_pod("kube-controller-manager-master-1", "kube-system",
                      cpu="200m", memory="0", node_name="master-1"),
        make_fake_pod("kube-scheduler-master-1", "kube-system", cpu="100m",
                      memory="0", node_name="master-1"),
    ]
    cluster.deployments = [
        # metrics-server: masters only; required anti-affinity on a zone
        # key NO node carries (failure-domain.beta.kubernetes.io/zone) —
        # the vendored semantics admit the first pod of a term whose
        # topology key is absent everywhere
        make_fake_deployment(
            "metrics-server", "kube-system", replicas=1,
            match_labels={"k8s-app": "metrics-server"}, cpu="1", memory="500Mi",
            affinity={
                **MASTER_EXISTS,
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [{
                        "labelSelector": {"matchLabels": {"k8s-app": "metrics-server"}},
                        "topologyKey": "failure-domain.beta.kubernetes.io/zone",
                    }],
                },
            }),
    ]
    cluster.daemon_sets = [
        make_fake_daemonset(
            "kube-proxy-master", "kube-system",
            match_labels={"k8s-app": "kube-proxy-master"}, cpu="0", memory="0",
            tolerations=[{"operator": "Exists"}],
            node_selector={"node-role.kubernetes.io/master": ""}),
        make_fake_daemonset(
            "kube-proxy-worker", "kube-system",
            match_labels={"k8s-app": "kube-proxy-worker"}, cpu="0", memory="0",
            tolerations=[{"operator": "Exists"}],
            node_selector={"node-role.kubernetes.io/worker": ""}),
        make_fake_daemonset(
            "coredns", "kube-system",
            match_labels={"k8s-app": "coredns"}, cpu="100m", memory="70Mi",
            affinity=MASTER_EXISTS,
            tolerations=[{"key": "node-role.kubernetes.io/master",
                          "effect": "NoSchedule"}],
            node_selector={"beta.kubernetes.io/os": "linux"}),
    ]
    return cluster


def build_app() -> ClusterResources:
    app = ClusterResources()
    master_tol = [{"key": "node-role.kubernetes.io/master",
                   "operator": "Exists", "effect": "NoSchedule"}]
    app.deployments = [
        make_fake_deployment("busybox-deploy", "simple", replicas=4,
                             match_labels={"app": "busybox-deploy"},
                             cpu="1500m", memory="1Gi", tolerations=master_tol),
    ]
    app.daemon_sets = [
        make_fake_daemonset(
            "busybox-ds", "simple", match_labels={"app": "busybox-ds"},
            cpu="500m", memory="512Mi",
            node_selector={"beta.kubernetes.io/os": "linux"},
            affinity={"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        {"key": "node-role.kubernetes.io/master",
                         "operator": "DoesNotExist"}]}]}}}),
    ]
    app.jobs = [
        make_fake_job("pi", "default", completions=1, cpu="100m", memory="100Mi"),
    ]
    app.pods = [
        make_fake_pod("single-pod", "simple", cpu="100m", memory="100Mi",
                      node_selector={"node-role.kubernetes.io/master": ""},
                      tolerations=master_tol),
    ]
    app.stateful_sets = [
        make_fake_statefulset(
            "busybox-sts", "simple", replicas=4,
            match_labels={"app": "busybox-sts"}, cpu="1", memory="512Mi",
            tolerations=master_tol,
            affinity={"podAntiAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [{
                    "weight": 100,
                    "podAffinityTerm": {
                        "labelSelector": {"matchExpressions": [
                            {"key": "app", "operator": "In",
                             "values": ["busybox-sts"]}]},
                        "topologyKey": "kubernetes.io/hostname",
                    },
                }]}}),
    ]
    app.replica_sets = [
        make_fake_replicaset(
            "calico-kube-controllers", "kube-system", replicas=2,
            match_labels={"k8s-app": "calico-kube-controllers"},
            cpu="0", memory="0",
            tolerations=[
                {"effect": "NoSchedule", "operator": "Exists"},
                {"key": "CriticalAddonsOnly", "operator": "Exists"},
                {"effect": "NoExecute", "operator": "Exists"},
            ]),
    ]
    return app


def test_reference_simple_scenario_check_result():
    cluster = build_cluster()
    app = build_app()
    result = simulate(cluster, [AppResource(name="simple", resources=app)])

    # checkResult invariant 1: failedPodsNum == 0 (core_test.go:304)
    assert not result.unscheduled_pods, [
        (u.pod.key, u.reason) for u in result.unscheduled_pods
    ]

    placements = result.placements()

    # checkResult invariant 2: individual pods all placed (static pods on
    # their pinned node; single-pod on a master)
    for name in ("etcd-master-1", "kube-apiserver-master-1",
                 "kube-controller-manager-master-1", "kube-scheduler-master-1"):
        assert placements[f"kube-system/{name}"] == "master-1"
    assert placements["simple/single-pod"] in ("master-1", "master-2", "master-3")

    # checkResult invariant 3: per-workload recount — expected replicas
    # equal pods found, workload membership via name prefix + namespace
    def count(ns, prefix):
        return sum(1 for k in placements if k.startswith(f"{ns}/{prefix}"))

    expected_replicas = {
        ("kube-system", "metrics-server"): 1,
        ("simple", "busybox-deploy"): 4,
        ("default", "pi"): 1,
        ("simple", "busybox-sts"): 4,
        ("kube-system", "calico-kube-controllers"): 2,
    }
    for (ns, name), want in expected_replicas.items():
        assert count(ns, name) == want, (ns, name, count(ns, name), want)

    # checkResult invariant 4: DaemonSet placement re-derived per node via
    # the daemon-controller predicates (core_test.go:429-437 NodeShouldRunPod)
    all_ds = [("kube-system", ds) for ds in cluster.daemon_sets]
    all_ds += [("simple", ds) for ds in app.daemon_sets]
    for ns, ds in all_ds:
        expected_nodes = {
            n.name for n in cluster.nodes if daemonset_node_should_run(ds, n)
        }
        actual_nodes = {
            v for k, v in placements.items()
            if k.startswith(f"{ns}/{ds.meta.name}")
        }
        assert actual_nodes == expected_nodes, (ds.meta.name, actual_nodes, expected_nodes)
    # spelled out: proxy-master on the 3 masters (tolerates the taint),
    # proxy-worker + busybox-ds on the worker, coredns on the masters
    assert {v for k, v in placements.items()
            if k.startswith("kube-system/kube-proxy-master")} == {
        "master-1", "master-2", "master-3"}
    assert {v for k, v in placements.items()
            if k.startswith("kube-system/kube-proxy-worker")} == {"worker-1"}
    assert {v for k, v in placements.items()
            if k.startswith("simple/busybox-ds")} == {"worker-1"}
    assert {v for k, v in placements.items()
            if k.startswith("kube-system/coredns")} == {
        "master-1", "master-2", "master-3"}

    # semantic spot-checks beyond the reference's oracle:
    # metrics-server required a master and the zone-keyed anti-affinity
    # (key absent on every node) did not block its first pod
    ms_node = next(v for k, v in placements.items()
                   if k.startswith("kube-system/metrics-server"))
    assert ms_node in ("master-2", "master-3")  # master-1 is tainted
    # busybox-deploy pods never on the tainted master without capacity...
    # they tolerate the taint, so masters are allowed; just recount totals
    per_node = Counter(placements.values())
    assert sum(per_node.values()) == len(placements)

    # checkResult invariant 5 (implicit in the reference via the real
    # scheduler): no node over-packed on cpu/memory
    for ns_status in result.node_status:
        alloc = ns_status.node.allocatable
        totals = Counter()
        for p in ns_status.pods:
            for r, v in p.requests().items():
                totals[r] += v
        for r, used in totals.items():
            assert used <= alloc.get(r, 0) + 1e-6, (ns_status.node.name, r, used)

    # the preferred hostname anti-affinity pushes the 4 sts pods apart —
    # it is ONE normalized score among many (the vendored scheduler
    # guarantees no perfect spread either), so assert meaningful spreading
    # rather than perfection
    sts_nodes = [v for k, v in placements.items() if k.startswith("simple/busybox-sts")]
    assert len(set(sts_nodes)) >= 3
