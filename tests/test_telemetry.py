"""Telemetry layer: registry + Prometheus rendering, spans + Chrome
trace, utils/trace coverage, stack instrumentation, and the REST
/metrics + /api/explain surfaces."""

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from open_simulator_tpu import telemetry
from open_simulator_tpu.telemetry.registry import (
    MetricsRegistry,
    PROMETHEUS_CONTENT_TYPE,
)
from open_simulator_tpu.telemetry.spans import SpanRecorder, span


# ---- registry ------------------------------------------------------------


def test_counter_inc_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help", labelnames=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2)
    c.labels(kind="b").inc()
    assert c.value(kind="a") == 3
    assert c.value(kind="b") == 1
    assert c.value(kind="missing") == 0
    with pytest.raises(ValueError):
        c.labels(kind="a").inc(-1)


def test_counter_without_labels_and_label_mismatch():
    reg = MetricsRegistry()
    c = reg.counter("plain_total")
    c.inc()
    rendered = reg.render_prometheus()
    assert "plain_total 1" in rendered
    labeled = reg.counter("lab_total", labelnames=("x",))
    with pytest.raises(ValueError):
        labeled.inc()  # must go through .labels()
    with pytest.raises(ValueError):
        labeled.labels(wrong="v")


def test_get_or_create_is_idempotent_and_type_safe():
    reg = MetricsRegistry()
    a = reg.counter("same_total", "h")
    b = reg.counter("same_total", "h")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("same_total")
    with pytest.raises(ValueError):
        reg.counter("same_total", labelnames=("other",))
    with pytest.raises(ValueError):
        reg.counter("bad name")


def test_histogram_bucket_mismatch_rejected():
    reg = MetricsRegistry()
    reg.histogram("hb_seconds", buckets=(0.1, 1.0))
    assert reg.histogram("hb_seconds", buckets=(1.0, 0.1)) is not None  # order-insensitive
    with pytest.raises(ValueError):
        reg.histogram("hb_seconds", buckets=(5.0,))


def test_gauge_set_inc_dec():
    reg = MetricsRegistry()
    g = reg.gauge("g_val")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value() == 6
    gl = reg.gauge("g_lab", labelnames=("d",))
    gl.labels(d="x").set(1.5)
    assert gl.value(d="x") == 1.5


def test_gauge_callback_sampled_at_render_and_survives_errors():
    reg = MetricsRegistry()
    g = reg.gauge("cb_val", labelnames=("k",))
    g.set_callback(lambda: {("a",): 2.0, ("b",): 3.0})
    out = reg.render_prometheus()
    assert 'cb_val{k="a"} 2' in out and 'cb_val{k="b"} 3' in out

    def boom():
        raise RuntimeError("introspection moved")

    g.set_callback(boom)
    out = reg.render_prometheus()  # must not raise
    assert "# TYPE cb_val gauge" in out and 'cb_val{k="a"}' not in out


def test_histogram_buckets_sum_count():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    out = reg.render_prometheus()
    assert 'h_seconds_bucket{le="0.1"} 1' in out
    assert 'h_seconds_bucket{le="1"} 3' in out
    assert 'h_seconds_bucket{le="10"} 4' in out
    assert 'h_seconds_bucket{le="+Inf"} 5' in out
    assert "h_seconds_count 5" in out
    assert "h_seconds_sum 56.05" in out
    assert h.child_stats() == (5, 56.05)


def test_prometheus_text_format_shape_and_escaping():
    reg = MetricsRegistry()
    c = reg.counter("fmt_total", "an \"odd\" help", labelnames=("p",))
    c.labels(p='we"ird\nvalue\\x').inc()
    text = reg.render_prometheus()
    lines = text.strip().splitlines()
    assert lines[0].startswith("# HELP fmt_total")
    assert lines[1] == "# TYPE fmt_total counter"
    # label escaping: backslash, newline, quote
    assert 'p="we\\"ird\\nvalue\\\\x"' in lines[2]
    assert text.endswith("\n")


# ---- spans + chrome trace ------------------------------------------------


def test_spans_nest_and_export_chrome_trace(tmp_path):
    rec = SpanRecorder()
    with span("outer", recorder=rec):
        with span("inner", recorder=rec, detail="x"):
            pass
    records = rec.records()
    by_name = {r.name: r for r in records}
    assert by_name["inner"].depth == 1 and by_name["outer"].depth == 0
    # containment: inner happens inside outer's interval
    o, i = by_name["outer"], by_name["inner"]
    assert o.t0 <= i.t0 and i.t0 + i.dur <= o.t0 + o.dur + 1e-9

    path = tmp_path / "trace.json"
    rec.export_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert {e["name"] for e in events} == {"outer", "inner"}
    for e in events:
        assert e["ph"] == "X" and "ts" in e and "dur" in e and "pid" in e
    inner_ev = next(e for e in events if e["name"] == "inner")
    assert inner_ev["args"] == {"detail": "x"}


def test_span_closes_on_exception_and_feeds_histogram():
    rec = SpanRecorder()
    h = telemetry.histogram(
        "simon_phase_seconds", labelnames=("phase",))
    before = h.child_stats(phase="failing")[0]
    with pytest.raises(RuntimeError):
        with span("failing", recorder=rec):
            raise RuntimeError("boom")
    assert [r.name for r in rec.records()] == ["failing"]
    assert h.child_stats(phase="failing")[0] == before + 1


def test_recorder_clear_and_bound():
    rec = SpanRecorder(maxlen=4)
    for i in range(10):
        rec.add(f"s{i}", 0.0, 0.001)
    assert len(rec.records()) == 4
    rec.clear()
    assert rec.records() == []


# ---- utils/trace.py (previously untested) --------------------------------


def test_trace_warn_branch(caplog):
    from open_simulator_tpu.utils.trace import Trace

    t = Trace("Simulate", warn_after_s=0.0)  # always trips the alarm
    with t.step("encode"):
        pass
    with caplog.at_level(logging.WARNING, logger="simon-tpu.trace"):
        total = t.finish()
    assert total >= 0
    [rec] = [r for r in caplog.records if r.name == "simon-tpu.trace"]
    assert "Simulate took" in rec.getMessage()
    assert "encode:" in rec.getMessage()


def test_trace_quiet_branch_logs_debug_only(caplog):
    from open_simulator_tpu.utils.trace import Trace

    t = Trace("Fast", warn_after_s=3600.0)
    with t.step("s"):
        pass
    with caplog.at_level(logging.DEBUG, logger="simon-tpu.trace"):
        t.finish()
    [rec] = [r for r in caplog.records if r.name == "simon-tpu.trace"]
    assert rec.levelno == logging.DEBUG


def test_trace_steps_feed_span_recorder():
    from open_simulator_tpu.telemetry.spans import RECORDER
    from open_simulator_tpu.utils.trace import Trace

    t = Trace("Wired", warn_after_s=3600.0)
    with t.step("phase-x"):
        pass
    assert any(r.name == "phase-x" for r in RECORDER.records())


def test_profile_to_noop_without_dir():
    from open_simulator_tpu.utils.trace import profile_to

    with profile_to(None):  # must not import jax.profiler or raise
        marker = True
    assert marker


# ---- engine/sched_config rename ------------------------------------------


def test_engine_profile_shim_is_retired():
    """The engine/profile.py deprecation shim (left by the PR-3 rename
    to sched_config.py) is RETIRED: the module must no longer import,
    and the real module keeps exporting the public names. This test
    pins the retirement so the shim cannot quietly come back."""
    import importlib

    import pytest

    from open_simulator_tpu.engine import sched_config

    with pytest.raises(ModuleNotFoundError):
        importlib.import_module("open_simulator_tpu.engine.profile")
    assert callable(sched_config.weight_overrides_from_file)
    assert issubclass(sched_config.SchedulerConfigError, Exception)


# ---- stack instrumentation ----------------------------------------------


def _tiny_body():
    return {
        "cluster": {"yaml": (
            "apiVersion: v1\nkind: Node\nmetadata: {name: m0}\n"
            "status:\n  allocatable: {cpu: '4', memory: 8Gi, pods: '110'}\n")},
        "apps": [{"name": "a", "yaml": (
            "apiVersion: v1\nkind: Pod\nmetadata: {name: p, namespace: default}\n"
            "spec:\n  containers:\n    - name: c\n      resources:\n"
            "        requests: {cpu: 100m}\n")}],
    }


def test_simulate_records_phases_and_compile_cache(node_factory, pod_factory):
    from open_simulator_tpu.core import AppResource, simulate
    from open_simulator_tpu.k8s.loader import ClusterResources

    phase = telemetry.histogram("simon_phase_seconds", labelnames=("phase",))
    sims = telemetry.counter("simon_simulations_total")
    before = {p: phase.child_stats(phase=p)[0]
              for p in ("simulate", "encode", "schedule", "decode")}
    sims_before = sims.value()

    cluster = ClusterResources()
    cluster.nodes = [node_factory("t0")]
    apps = ClusterResources()
    apps.pods = [pod_factory("t-pod")]
    result = simulate(cluster, [AppResource("a", apps)])
    assert len(result.scheduled_pods) == 1

    for p, n0 in before.items():
        assert phase.child_stats(phase=p)[0] == n0 + 1, f"phase {p} not recorded"
    assert sims.value() == sims_before + 1
    # compile-cache accounting saw the schedule phase (hit or miss,
    # depending on what earlier tests compiled)
    cache = telemetry.counter(
        "simon_compile_cache_total", labelnames=("fn", "event"))
    assert (cache.value(fn="schedule_pods", event="hit")
            + cache.value(fn="schedule_pods", event="miss")) >= 1


def test_admission_rejections_counted():
    from open_simulator_tpu.errors import AdmissionError
    from open_simulator_tpu.k8s.loader import ClusterResources
    from open_simulator_tpu.k8s.objects import Node
    from open_simulator_tpu.resilience.admission import admit

    c = telemetry.counter(
        "simon_admission_rejections_total", labelnames=("code",))
    before = c.value(code="E_QUANTITY")
    cluster = ClusterResources()
    cluster.nodes = [Node.from_dict({
        "metadata": {"name": "bad"},
        "status": {"allocatable": {"cpu": "-2", "memory": "1Gi", "pods": "10"}},
    })]
    with pytest.raises(AdmissionError):
        admit(cluster)
    assert c.value(code="E_QUANTITY") == before + 1


def test_retry_outcomes_counted():
    from open_simulator_tpu.resilience.retry import run_with_retries

    c = telemetry.counter("simon_retry_total", labelnames=("outcome",))
    b_retried = c.value(outcome="retried")
    b_recovered = c.value(outcome="recovered")
    b_exhausted = c.value(outcome="exhausted")

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("transient")
        return "ok"

    assert run_with_retries(flaky, retries=2, sleep=lambda _s: None) == "ok"
    assert c.value(outcome="retried") == b_retried + 1
    assert c.value(outcome="recovered") == b_recovered + 1

    with pytest.raises(OSError):
        run_with_retries(lambda: (_ for _ in ()).throw(OSError("hard")),
                         retries=1, sleep=lambda _s: None)
    assert c.value(outcome="exhausted") == b_exhausted + 1


# ---- REST: /metrics, /api/explain, access log ---------------------------


@pytest.fixture(scope="module")
def telemetry_server():
    from open_simulator_tpu.server.rest import SimulationServer, _make_handler

    httpd = ThreadingHTTPServer(
        ("127.0.0.1", 0), _make_handler(SimulationServer()))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.status, resp.headers, resp.read().decode()


def _post(url, payload):
    req = urllib.request.Request(url, data=json.dumps(payload).encode())
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def test_explain_404_before_any_simulation(telemetry_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(telemetry_server + "/api/explain")
    assert ei.value.code == 404
    assert json.loads(ei.value.read())["code"] == "E_NO_SIMULATION"


def test_metrics_endpoint_serves_core_series(telemetry_server, caplog):
    with caplog.at_level(logging.DEBUG, logger="simon-tpu.http"):
        out = _post(telemetry_server + "/api/deploy-apps", _tiny_body())
        assert not out["unscheduled_pods"]
        # the access log routed method/path/status/duration through the
        # logger; the server thread writes it AFTER flushing the response
        # body, so the client can observe the response first — wait out
        # that handoff instead of racing it
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            access = [r.getMessage() for r in caplog.records
                      if r.name == "simon-tpu.http"]
            if any("POST /api/deploy-apps -> 200" in m and "ms" in m
                   for m in access):
                break
            time.sleep(0.02)
        else:
            raise AssertionError(f"no timed access-log line in {access}")

    status, headers, text = _get(telemetry_server + "/metrics")
    assert status == 200
    assert headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
    for series in ("simon_http_requests_total", "simon_http_request_seconds",
                   "simon_http_in_flight", "simon_phase_seconds",
                   "simon_simulations_total", "simon_pods_scheduled_total",
                   "simon_admission_rejections_total",
                   "simon_compile_cache_total", "simon_jax_devices"):
        assert series in text, f"missing {series}"
    # the request metric carries the method/path/status labels
    assert 'simon_http_requests_total{method="POST",path="/api/deploy-apps",status="200"}' in text
    # prometheus text format: every non-comment line is "name{...} value"
    import re

    sample = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+$")
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert sample.match(line), f"malformed sample line: {line!r}"


def test_explain_endpoint_over_last_result(telemetry_server):
    body = _tiny_body()
    # one schedulable pod + one impossible pod, so explain has both a
    # candidate breakdown and a failure decode
    body["apps"][0]["yaml"] += (
        "---\n"
        "apiVersion: v1\nkind: Pod\nmetadata: {name: q, namespace: default}\n"
        "spec:\n  containers:\n    - name: c\n      resources:\n"
        "        requests: {cpu: '64'}\n")
    out = _post(telemetry_server + "/api/deploy-apps", body)
    assert out["unscheduled_pods"]
    _status, _h, text = _get(telemetry_server + "/api/explain?top_k=1")
    report = json.loads(text)
    unsched = [p for p in report["pods"] if p["status"] == "unscheduled"]
    assert unsched and unsched[0]["first_failing_op"] == "Insufficient cpu"
    assert unsched[0]["eliminations"] == [{"op": "Insufficient cpu", "nodes": 1}]
    # serving simulations record explain_topk, so scheduled pods carry a
    # candidate breakdown without any re-run
    sched = next(p for p in report["pods"] if p["status"] == "scheduled")
    assert sched["candidates"], "server-side explain must have candidates"
    assert sched["candidates"][0]["node"] == sched["node"]
    assert set(sched["candidates"][0]["parts"]) == set(report["score_parts"])
    # pod filter
    key = unsched[0]["pod"]
    _s, _h, text = _get(telemetry_server + f"/api/explain?pod={key}")
    filtered = json.loads(text)
    assert [p["pod"] for p in filtered["pods"]] == [key]


def test_explain_endpoint_bad_topk(telemetry_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(telemetry_server + "/api/explain?top_k=abc")
    assert ei.value.code == 400


def test_unknown_paths_collapse_to_other_label(telemetry_server):
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(telemetry_server + "/definitely/not/a/route")
    _s, _h, text = _get(telemetry_server + "/metrics")
    assert 'path="other"' in text
    assert 'path="/definitely/not/a/route"' not in text
