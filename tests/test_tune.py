"""Policy tuning on the lane axis (tune/, ARCHITECTURE.md §17).

Covers the tentpole contracts of ISSUE 13:

* **traced-weights equivalence**: the traced path at the config's own
  weight vector is BIT-IDENTICAL (outputs, final state, ledger digest,
  explain topk_parts) to the constant-weight path — across the easy /
  rich / pools / gpu workloads, waves on and off, singleton and lane
  execution, and both capacity-sweep modes (exhaustive + bisect) under
  a traced config;
* **one executable**: a whole tune run (W variants x R rounds, grid and
  cem) compiles exactly one new batched executable, asserted via the
  `simon_compile_cache_total` miss delta;
* **Pareto honesty**: the report's Pareto set equals a brute-force
  O(W^2) dominance sweep AND one-variant-at-a-time enumeration of the
  same vectors;
* **scheduler-config fuzz**: ~50-seed mutation fuzz of
  KubeSchedulerConfiguration parsing — every malformation is a
  structured E_SPEC (CLI `error:` exit, REST 400), never a traceback;
* **fleet lanes**: same-bucket campaign clusters execute in FEWER
  launches than clusters with a report digest bit-identical to the
  serial boundary, and per-lane quarantine isolates one poisoned lane
  while its siblings settle.
"""

from __future__ import annotations

import copy
import json
import random
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest
import yaml

import jax.numpy as jnp

from open_simulator_tpu.encode.snapshot import encode_cluster
from open_simulator_tpu.engine.scheduler import (
    WEIGHT_FIELDS,
    device_arrays,
    make_config,
    schedule_pods,
    score_part_names,
    weight_vector,
)
from open_simulator_tpu.engine.sched_config import (
    MOST_ALLOCATED_OVERRIDES,
    SchedulerConfigError,
    weight_overrides_from_file,
    weight_overrides_from_text,
)
from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.telemetry.ledger import array_result_digest
from open_simulator_tpu.testing.synthetic import synthetic_snapshot
from tests.conftest import make_node, make_pod

OUT_FIELDS = ("node", "fail_counts", "feasible", "gpu_pick", "vol_pick",
              "topk_node", "topk_score", "topk_parts")


def _gpu_snapshot(n_nodes=6, n_pods=18):
    from open_simulator_tpu.k8s.objects import (
        ANNO_GPU_COUNT,
        ANNO_GPU_MEM,
        RES_GPU_COUNT,
        RES_GPU_MEM,
    )

    nodes = [make_node(f"g{i}", cpu_m=16000, mem_mib=65536,
                       extra_alloc={RES_GPU_COUNT: 2, RES_GPU_MEM: 32},
                       labels={"topology.kubernetes.io/zone": f"z{i % 2}"})
             for i in range(n_nodes)]
    pods = [make_pod(f"p{i}", cpu="500m",
                     annotations={ANNO_GPU_MEM: str(4 + i % 3),
                                  ANNO_GPU_COUNT: "1"})
            for i in range(n_pods)]
    return encode_cluster(nodes, pods)


def _snapshot(name):
    if name == "easy":
        return synthetic_snapshot(10, 40, 0)
    if name == "rich":
        return synthetic_snapshot(10, 40, 0, rich=True)
    if name == "pools":
        return synthetic_snapshot(12, 48, 0, pools=4)
    if name == "gpu":
        return _gpu_snapshot()
    raise AssertionError(name)


def _assert_outputs_identical(out_a, out_b, what=""):
    for name in OUT_FIELDS:
        a = np.asarray(getattr(out_a, name))
        b = np.asarray(getattr(out_b, name))
        assert np.array_equal(a, b), f"{what}: {name} diverged"
    for name, a in out_a.state._asdict().items():
        b = getattr(out_b.state, name)
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"{what}: state.{name} diverged")
    assert (array_result_digest(np.asarray(out_a.node))
            == array_result_digest(np.asarray(out_b.node))), (
        f"{what}: ledger digest diverged")


# ---- traced-weights equivalence (the bit-identical contract) -------------


@pytest.mark.parametrize("waves", [False, True], ids=["scan", "waves"])
@pytest.mark.parametrize("name", ["easy", "rich", "pools", "gpu"])
def test_traced_default_vector_is_digest_identical(name, waves):
    """Constant path vs traced path at the config's own weight_vector():
    every output tensor, every carry leaf, the ledger result digest, and
    the explain topk_parts rows must be bit-identical. explain_topk runs
    on the rich workload only — it is the one whose part table holds
    every score row, and compiling the topk machinery into all four
    workloads is pure tier-1 wall time."""
    from open_simulator_tpu.engine.waves import waves_for

    snap = _snapshot(name)
    cfg = make_config(snap, explain_topk=2 if name == "rich" else 0)
    arrs = device_arrays(snap)
    cfg_t = cfg._replace(traced_weights=True)
    plan_c = waves_for(snap.arrays, cfg) if waves else None
    plan_t = waves_for(snap.arrays, cfg_t) if waves else None
    out_c = schedule_pods(arrs, arrs.active, cfg, waves=plan_c)
    out_t = schedule_pods(arrs, arrs.active, cfg_t, waves=plan_t,
                          weights=jnp.asarray(weight_vector(cfg)))
    # identical part-row vocabulary first (topk_parts rows must agree)
    assert score_part_names(cfg) == score_part_names(cfg_t)
    _assert_outputs_identical(out_c, out_t, f"{name}/waves={waves}")


def test_traced_config_without_explicit_weights_bakes_own_vector():
    """Omitting `weights` under a traced config runs the config's own
    vector — still digest-identical to the constant path (the capacity
    sweeps rely on this to accept traced configs unchanged)."""
    snap = _snapshot("easy")
    cfg = make_config(snap)
    arrs = device_arrays(snap)
    out_c = schedule_pods(arrs, arrs.active, cfg)
    out_t = schedule_pods(arrs, arrs.active,
                          cfg._replace(traced_weights=True))
    _assert_outputs_identical(out_c, out_t, "baked-default")


def test_traced_weights_shape_and_mode_validation():
    snap = _snapshot("easy")
    cfg = make_config(snap)
    arrs = device_arrays(snap)
    with pytest.raises(ValueError, match="traced_weights is off"):
        schedule_pods(arrs, arrs.active, cfg,
                      weights=jnp.zeros(len(WEIGHT_FIELDS)))
    with pytest.raises(ValueError, match="WEIGHT_FIELDS"):
        schedule_pods(arrs, arrs.active,
                      cfg._replace(traced_weights=True),
                      weights=jnp.zeros(3))


def test_traced_nondefault_vector_matches_constant_config():
    """A traced run at a NON-default vector answers the same question as
    a constant config built with those weights — the semantic contract
    that makes a tune lane a real policy variant. (Assignments equal;
    score parts are not compared: a zero constant weight compiles its
    row out while the traced path keeps it live at +0.0.)"""
    snap = _snapshot("easy")
    arrs = device_arrays(snap)
    variant = dict(MOST_ALLOCATED_OVERRIDES)  # the bin-packing profile
    cfg_c = make_config(snap, **variant)
    cfg_t = make_config(snap, **variant)._replace(traced_weights=True)
    out_c = schedule_pods(arrs, arrs.active, cfg_c)
    out_t = schedule_pods(arrs, arrs.active, cfg_t,
                          weights=jnp.asarray(weight_vector(cfg_c)))
    assert np.array_equal(np.asarray(out_c.node), np.asarray(out_t.node))
    assert np.array_equal(np.asarray(out_c.fail_counts),
                          np.asarray(out_t.fail_counts))


def test_traced_lanes_match_singleton_runs():
    """[W, K] lane execution: each lane of one batched traced launch is
    bit-identical to its singleton traced run (the vmap adds no
    cross-lane ops) — the property every tune round leans on."""
    from open_simulator_tpu.engine import exec_cache

    snap = _snapshot("easy")
    cfg = make_config(snap)._replace(traced_weights=True,
                                     fail_reasons=False)
    arrs, _, n_pods = exec_cache.bucketed_device_arrays(snap.arrays)
    base = weight_vector(cfg)
    variants = [base,
                np.asarray([1, 0, 1, 2, 0, 1, 0, 1, 1], np.float32),
                np.asarray([0, 2, 0, 0, 1, 0, 4, 0, 1], np.float32)]
    wmat = np.stack(variants)
    masks = np.tile(np.asarray(arrs.active), (len(variants), 1))
    out = exec_cache.run_batched_cached(arrs, masks, cfg, weights=wmat)
    for i, vec in enumerate(variants):
        solo = schedule_pods(arrs, arrs.active, cfg,
                             weights=jnp.asarray(vec))
        assert np.array_equal(np.asarray(out.node)[i],
                              np.asarray(solo.node)), f"lane {i}"


@pytest.mark.parametrize("mode", ["exhaustive", "bisect"])
def test_capacity_sweeps_accept_traced_config(mode):
    """Both sweep modes under a traced config (no explicit weights) give
    the same plan as the constant config — best_count and per-lane
    assignments included."""
    from open_simulator_tpu.parallel.sweep import (
        capacity_bisect,
        capacity_sweep,
    )

    snap = synthetic_snapshot(6, 24, max_new=4)
    cfg_c = make_config(snap)._replace(fail_reasons=False)
    cfg_t = cfg_c._replace(traced_weights=True)
    if mode == "exhaustive":
        plan_c = capacity_sweep(snap, cfg_c, [0, 2, 4])
        plan_t = capacity_sweep(snap, cfg_t, [0, 2, 4])
    else:
        # lanes == len(counts) above so both modes share the two
        # 3-lane executables (constant + traced) — one compile pair
        # serves the whole parametrization
        plan_c = capacity_bisect(snap, cfg_c, 4, lanes=3)
        plan_t = capacity_bisect(snap, cfg_t, 4, lanes=3)
    assert plan_c.best_count == plan_t.best_count
    assert plan_c.counts == plan_t.counts
    assert np.array_equal(np.asarray(plan_c.nodes_per_scenario),
                          np.asarray(plan_t.nodes_per_scenario))


def test_traced_mode_forks_the_exec_cache_key():
    """Tuned and constant runs must never share an executable: the
    traced_weights flag is part of EngineConfig, so it forks the AOT
    cache key (a stale alias would answer with the wrong program)."""
    from open_simulator_tpu import telemetry
    from open_simulator_tpu.engine import exec_cache

    snap = _snapshot("easy")
    cfg = make_config(snap)._replace(fail_reasons=False)
    arrs, _, _ = exec_cache.bucketed_device_arrays(snap.arrays)
    masks = np.tile(np.asarray(arrs.active), (2, 1))
    c = telemetry.counter("simon_compile_cache_total",
                          labelnames=("fn", "event"))
    exec_cache.run_batched_cached(arrs, masks, cfg)
    m0 = c.value(fn="batched_schedule", event="miss")
    exec_cache.run_batched_cached(arrs, masks,
                                  cfg._replace(traced_weights=True))
    m1 = c.value(fn="batched_schedule", event="miss")
    assert m1 == m0 + 1, "traced config aliased the constant executable"


# ---- the search (tune/search.py) -----------------------------------------


def _tune_cluster(n_nodes=6, n_pods=18):
    """A small cluster where weights actually matter: two node classes
    (big/small), a soft zone spread, pods that fit everywhere."""
    from open_simulator_tpu.k8s.loader import ClusterResources

    cluster = ClusterResources()
    for i in range(n_nodes):
        cluster.nodes.append(make_node(
            f"n{i}", cpu_m=16000 if i % 2 else 8000,
            mem_mib=32768 if i % 2 else 16384,
            labels={"topology.kubernetes.io/zone": f"z{i % 2}"}))
    for i in range(n_pods):
        cluster.pods.append(make_pod(
            f"p{i}", cpu="900m", mem="900Mi",
            labels={"app": f"a{i % 3}"},
            spread=[{"maxSkew": 1,
                     "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": "ScheduleAnyway",
                     "labelSelector": {"matchLabels":
                                       {"app": f"a{i % 3}"}}}]))
    return cluster


def test_tune_grid_one_executable_and_brute_force_pareto():
    from open_simulator_tpu import telemetry
    from open_simulator_tpu.tune import (
        TuneOptions,
        brute_force_pareto,
        tune_search,
    )

    cluster = _tune_cluster()
    c = telemetry.counter("simon_compile_cache_total",
                          labelnames=("fn", "event"))
    m0 = c.value(fn="batched_schedule", event="miss")
    rep = tune_search(cluster, [], TuneOptions(
        mode="grid", variants=4, grid_values=(0.0, 2.0)))
    m1 = c.value(fn="batched_schedule", event="miss")
    assert m1 - m0 == 1, "a tune run must compile exactly ONE executable"
    assert rep["rounds_run"] > 1          # several rounds, still 1 compile
    assert rep["n_variants"] == len(rep["points"])
    # lane one of round one is the baseline; disruption self-measures 0
    assert rep["baseline"]["disruption"] == 0
    bf = brute_force_pareto(rep["points"])
    assert [p["vector"] for p in rep["pareto"]] == [p["vector"] for p in bf]
    # a second search on the same bucket (cem, same lane count) reuses it
    rep2 = tune_search(cluster, [], TuneOptions(
        mode="cem", variants=4, rounds=2, seed=7))
    m2 = c.value(fn="batched_schedule", event="miss")
    assert m2 == m1, "cem rounds recompiled"
    assert rep2["n_variants"] >= 4
    bf2 = brute_force_pareto(rep2["points"])
    assert [p["vector"] for p in rep2["pareto"]] == [p["vector"]
                                                     for p in bf2]


def test_tune_pareto_matches_single_variant_enumeration():
    """Every reported point re-verified one variant at a time: a
    singleton traced run of each vector must reproduce the point's
    (unplaced, cost, disruption) exactly, and the Pareto set over the
    re-derived points must equal the report's."""
    from open_simulator_tpu.core import build_pod_sequence
    from open_simulator_tpu.engine import exec_cache
    from open_simulator_tpu.k8s.loader import make_valid_node
    from open_simulator_tpu.tune import (
        TuneOptions,
        pareto_points,
        tune_search,
    )

    cluster = _tune_cluster()
    rep = tune_search(cluster, [], TuneOptions(mode="cem", variants=4,
                                               rounds=2, seed=3))
    nodes = [make_valid_node(n) for n in cluster.nodes]
    pods = build_pod_sequence(cluster, [])
    snap = encode_cluster(nodes, pods)
    cfg = make_config(snap, traced_weights=True)._replace(
        fail_reasons=False)
    arrs, _, n_pods = exec_cache.bucketed_device_arrays(snap.arrays)
    baseline_row = None
    rederived = []
    for p in rep["points"]:
        out = schedule_pods(
            arrs, arrs.active, cfg,
            weights=jnp.asarray(np.asarray(p["vector"], np.float32)))
        row = np.asarray(out.node)[:n_pods]
        if baseline_row is None:
            baseline_row = row
        placed = row >= 0
        rederived.append({
            "vector": p["vector"],
            "unplaced": int(np.sum(~placed)),
            "cost": int(np.unique(row[placed]).size),
            "disruption": int(np.sum(row != baseline_row)),
        })
        for k in ("unplaced", "cost", "disruption"):
            assert rederived[-1][k] == p[k], (k, p)
    assert ([p["vector"] for p in pareto_points(rederived)]
            == [p["vector"] for p in rep["pareto"]])


def test_tune_objectives_are_not_degenerate():
    """The search must actually discriminate: on a cluster with slack, a
    bin-packing-leaning variant occupies fewer nodes than the baseline
    spread-leaning policy (cost objective moves), so the Pareto set has
    more than one point."""
    from open_simulator_tpu.tune import TuneOptions, tune_search

    # same cluster shape and lane count as the searches above, so this
    # reuses their [4, K] executable instead of compiling an 8-lane one
    rep = tune_search(_tune_cluster(), [], TuneOptions(
        mode="grid", variants=4, grid_values=(0.0, 4.0)))
    costs = {p["cost"] for p in rep["points"]}
    assert len(costs) > 1, "no weight vector changed the placement"


def test_tune_options_validation_is_structured():
    from open_simulator_tpu.tune import TuneOptions

    for body, field in [
        ({"mode": "anneal"}, "mode"),
        ({"variants": 0}, "variants"),
        ({"variants": 10_000}, "variants"),
        ({"variants": 8.9}, "variants"),     # silent truncation is a lie
        ({"variants": True}, "variants"),    # bools float() to 0/1
        ({"sigma": True}, "sigma"),
        ({"weights": {"w_spread": True}}, "weights.w_spread"),
        ({"grid_values": [True]}, "grid_values[0]"),
        ({"grid_values": [0.0] * 65}, "grid_values"),
        ({"rounds": -1}, "rounds"),
        ({"rounds": "many"}, "rounds"),
        ({"grid_values": []}, "grid_values"),
        ({"grid_values": [1, "x"]}, "grid_values[1]"),
        ({"grid_values": [-1.0]}, "grid_values[0]"),
        ({"elite_frac": 0.0}, "elite_frac"),
        ({"sigma": float("nan")}, "sigma"),
        ({"max_weight": -2}, "max_weight"),
        ({"weights": ["w_spread"]}, "weights"),
        ({"weights": {"w_bogus": 1}}, "weights.w_bogus"),
        ({"weights": {"w_spread": -1}}, "weights.w_spread"),
        ({"weights": {"w_spread": "heavy"}}, "weights.w_spread"),
        # f64-finite but f32-inf: would NaN every score if accepted
        ({"weights": {"w_spread": 1e39}}, "weights.w_spread"),
        ({"grid_values": [1e39]}, "grid_values[0]"),
    ]:
        with pytest.raises(SimulationError) as ei:
            TuneOptions.from_body(body)
        assert ei.value.field == field, (body, ei.value.field)
        assert ei.value.code in ("E_BAD_REQUEST", "E_SPEC")


# ---- KubeSchedulerConfiguration fuzz -------------------------------------


BASE_SCHED_DOC = {
    "apiVersion": "kubescheduler.config.k8s.io/v1beta2",
    "kind": "KubeSchedulerConfiguration",
    "profiles": [{
        "schedulerName": "default-scheduler",
        "plugins": {
            "score": {
                "enabled": [
                    {"name": "PodTopologySpread", "weight": 2},
                    {"name": "NodeResourcesBalancedAllocation",
                     "weight": 1},
                ],
                "disabled": [{"name": "TaintToleration"}],
            },
            "filter": {
                "disabled": [{"name": "PodTopologySpread"}],
            },
        },
        "pluginConfig": [{
            "name": "NodeResourcesFit",
            "args": {"scoringStrategy": {"type": "MostAllocated"}},
        }],
    }],
}


def _mutate_sched_doc(rng: random.Random):
    """One random malformation of the valid base doc — the classes the
    satellite task names: dropped keys, wrong types, negative weights,
    unknown plugin names (plus a couple of structural smashes)."""
    doc = copy.deepcopy(BASE_SCHED_DOC)
    prof = doc["profiles"][0]
    score = prof["plugins"]["score"]
    mutation = rng.choice([
        "kind", "profiles_type", "profile_type", "plugins_type",
        "score_type", "enabled_type", "entry_type", "drop_name",
        "name_type", "weight_type", "weight_negative", "weight_nan",
        "unknown_score", "unknown_score_disabled", "filter_type",
        "plugin_config_type", "entry_cfg_type", "args_type",
        "strategy_type",
    ])
    if mutation == "kind":
        doc["kind"] = rng.choice(["Deployment", "KubeScheduler", 42])
    elif mutation == "profiles_type":
        doc["profiles"] = rng.choice(["default", 1, {"a": 1}])
    elif mutation == "profile_type":
        doc["profiles"][0] = rng.choice(["p", 3, ["x"]])
    elif mutation == "plugins_type":
        prof["plugins"] = rng.choice([["score"], "score", 7])
    elif mutation == "score_type":
        prof["plugins"]["score"] = rng.choice([["e"], "on", 1])
    elif mutation == "enabled_type":
        score["enabled"] = rng.choice([{"name": "x"}, "all", 5])
    elif mutation == "entry_type":
        score["enabled"][0] = rng.choice(["PodTopologySpread", 9, ["n"]])
    elif mutation == "drop_name":
        del score["enabled"][0]["name"]
    elif mutation == "name_type":
        score["enabled"][0]["name"] = rng.choice([17, None, ["x"], ""])
    elif mutation == "weight_type":
        score["enabled"][0]["weight"] = rng.choice(["heavy", [2], {}])
    elif mutation == "weight_negative":
        score["enabled"][0]["weight"] = -rng.randint(1, 100)
    elif mutation == "weight_nan":
        # round-trips through yaml as float nan / inf
        score["enabled"][0]["weight"] = rng.choice(
            [float("nan"), float("inf")])
    elif mutation == "unknown_score":
        score["enabled"][0]["name"] = f"OutOfTreeScore{rng.randint(0, 9)}"
    elif mutation == "unknown_score_disabled":
        score["disabled"][0]["name"] = f"Mystery{rng.randint(0, 9)}"
    elif mutation == "filter_type":
        prof["plugins"]["filter"] = rng.choice([["d"], "off", 2])
    elif mutation == "plugin_config_type":
        prof["pluginConfig"] = rng.choice([{"name": "x"}, "cfg", 4])
    elif mutation == "entry_cfg_type":
        prof["pluginConfig"][0] = rng.choice(["NodeResourcesFit", 6])
    elif mutation == "args_type":
        prof["pluginConfig"][0]["args"] = rng.choice([["s"], "args", 8])
    elif mutation == "strategy_type":
        prof["pluginConfig"][0]["args"]["scoringStrategy"] = rng.choice(
            [["t"], "MostAllocated", 3])
    return mutation, doc


def test_sched_config_base_doc_parses():
    ov = weight_overrides_from_text(yaml.safe_dump(BASE_SCHED_DOC))
    assert ov["w_spread"] == 2.0 and ov["w_balanced"] == 1.0
    assert ov["w_taint"] == 0.0           # explicit disable
    assert ov["w_most"] == 1.0            # MostAllocated strategy


def test_fuzz_sched_config_mutations_are_structured_espec(tmp_path):
    """~50 seeds: every mutated doc either still parses to a plain dict
    or raises SchedulerConfigError (E_SPEC, offending field named) —
    NOTHING else may escape (a KeyError/TypeError here would be a CLI
    traceback and a REST 500)."""
    rejected = 0
    for seed in range(50):
        mutation, doc = _mutate_sched_doc(random.Random(seed))
        path = tmp_path / f"cfg_{seed}.yaml"
        path.write_text(yaml.safe_dump(doc))
        try:
            ov = weight_overrides_from_file(str(path))
            assert isinstance(ov, dict), mutation
        except SchedulerConfigError as e:
            rejected += 1
            assert e.code == "E_SPEC", (mutation, e.code)
            assert isinstance(e.to_dict(), dict)
    # the fuzz must actually bite: most mutations are malformations
    assert rejected >= 25, f"only {rejected}/50 mutations rejected"


def test_sched_config_invalid_yaml_text_is_espec():
    with pytest.raises(SchedulerConfigError) as ei:
        weight_overrides_from_text("{unclosed: [")
    assert ei.value.code == "E_SPEC"


def test_cli_tune_bad_scheduler_config_is_error_exit(tmp_path, capsys):
    """The CLI surface of the same boundary: `simon-tpu tune` with a
    malformed scheduler config exits 1 with an `error:` line, never a
    traceback."""
    from open_simulator_tpu.cli.main import main

    bad = tmp_path / "bad.yaml"
    bad.write_text(yaml.safe_dump({
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"plugins": {"score": {
            "enabled": [{"name": "NoSuchPlugin"}]}}}]}))
    rc = main(["tune", "--cluster-config", "examples/cluster",
               "--scheduler-config", str(bad)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "error:" in err and "E_SPEC" in err and "NoSuchPlugin" in err


# ---- REST surface --------------------------------------------------------


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def tune_box():
    from open_simulator_tpu.server.rest import (
        SimulationServer,
        _make_handler,
    )

    srv = SimulationServer()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield srv, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()


def _cluster_yaml():
    cluster = _tune_cluster()
    return yaml.safe_dump_all(
        [{"apiVersion": "v1", "kind": "Node", **n.raw}
         for n in cluster.nodes]
        + [{"apiVersion": "v1", "kind": "Pod", **p.raw}
           for p in cluster.pods])


def test_rest_tune_grid_and_cem(tune_box):
    _, url = tune_box
    cy = _cluster_yaml()
    s, out = _post(url + "/api/tune",
                   {"cluster": {"yaml": cy}, "mode": "grid",
                    "variants": 4, "grid_values": [0, 2]})
    assert s == 200, out
    assert out["pareto"] and out["n_variants"] == len(out["points"])
    assert out["objectives"] == ["unplaced", "cost", "disruption"]
    s2, out2 = _post(url + "/api/tune",
                     {"cluster": {"yaml": cy}, "mode": "cem",
                      "variants": 4, "rounds": 2, "seed": 5})
    assert s2 == 200, out2
    assert out2["rounds_run"] == 2
    # determinism: the same seeded request reproduces its digest
    s3, out3 = _post(url + "/api/tune",
                     {"cluster": {"yaml": cy}, "mode": "cem",
                      "variants": 4, "rounds": 2, "seed": 5})
    assert s3 == 200 and out3["digest"] == out2["digest"]


def test_rest_tune_structured_400s(tune_box):
    _, url = tune_box
    cy = _cluster_yaml()
    for body, field in [
        ({"mode": "magic"}, "mode"),
        ({"variants": "lots"}, "variants"),
        ({"weights": {"w_nope": 1}}, "weights.w_nope"),
        ({"grid_values": [float("-1")]}, "grid_values[0]"),
        ({"scheduler_config": {"kind": "Deployment"}}, "kind"),
        ({"scheduler_config": "{broken: ["}, ""),
    ]:
        s, out = _post(url + "/api/tune", {"cluster": {"yaml": cy}, **body})
        assert s == 400, (body, s, out)
        assert out.get("field") == field, (body, out)
    # fuzzed scheduler_config docs inline: 400 or 200, never a 500
    for seed in range(12):
        _, doc = _mutate_sched_doc(random.Random(seed))
        s, out = _post(url + "/api/tune",
                       {"cluster": {"yaml": cy}, "variants": 1,
                        "rounds": 1, "scheduler_config": doc})
        assert s in (200, 400), (seed, s, out)
        if s == 400:
            assert out.get("code") in ("E_SPEC", "E_BAD_REQUEST")


def test_rest_tune_lapsed_deadline_is_504(tune_box):
    """An already-lapsed deadline 504s (skipped in queue or cancelled at
    the first round boundary) — never a 500, never device work burned."""
    _, url = tune_box
    s, out = _post(url + "/api/tune",
                   {"cluster": {"yaml": _cluster_yaml()},
                    "mode": "cem", "variants": 4, "rounds": 64,
                    "deadline_s": 1e-4})
    assert s == 504, out
    assert out["code"] in ("E_DEADLINE", "E_CANCELLED")


def test_tune_cancellation_at_round_boundary_carries_partial():
    """Cancellation is observed BETWEEN rounds with the tune partial
    shape (rounds_done / variants_done / pareto_so_far) — the payload a
    504 body carries."""
    from open_simulator_tpu.resilience import lifecycle
    from open_simulator_tpu.tune import TuneOptions, tune_search

    token = lifecycle.CancelToken(1e-6)
    with lifecycle.cancel_scope(token):
        with pytest.raises(lifecycle.CancelledError) as ei:
            tune_search(_tune_cluster(), [],
                        TuneOptions(mode="grid", variants=4))
    partial = ei.value.partial
    assert set(partial) >= {"tune_id", "rounds_done", "variants_done",
                            "pareto_so_far"}
    assert partial["rounds_done"] == 0


# ---- fleet lanes (campaign/lanes.py) -------------------------------------


def _write_fleet(tmp_path, n=4, poison_idx=None):
    from open_simulator_tpu.replay import synthetic_replay_cluster

    for i in range(n):
        path = tmp_path / f"c{i}.yaml"
        if i == poison_idx:
            path.write_text("{not: [valid yaml")   # quarantine fodder
            continue
        cl = synthetic_replay_cluster(n_nodes=6, n_initial_pods=12,
                                      cpu_m=4000 + 500 * i)
        path.write_text(yaml.safe_dump_all(
            [{"apiVersion": "v1", "kind": "Node", **n_.raw}
             for n_ in cl.nodes]
            + [{"apiVersion": "v1", "kind": "Pod", **p.raw}
               for p in cl.pods]))
    return str(tmp_path)


def test_fleet_lanes_fewer_launches_same_digest(tmp_path):
    """The §13 bucket map cashed in: 4 same-bucket clusters run as ONE
    launch (launches < clusters, the acceptance witness) and the report
    digest is bit-identical to the serial boundary's."""
    from open_simulator_tpu.campaign import CampaignOptions, run_campaign

    fleet = _write_fleet(tmp_path)
    serial = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=False, checkpoint=False))
    lanes = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=True, checkpoint=False))
    assert serial["totals"]["completed"] == 4
    assert lanes["digest"] == serial["digest"]
    assert serial["launches"] == 4
    assert lanes["launches"] < lanes["totals"]["clusters"]
    assert lanes["launches"] == 1
    assert len(lanes["buckets"]) == 1      # the bucket-map witness


def test_fleet_lanes_quarantine_digest_identical(tmp_path):
    """A poisoned cluster (unparseable dump) quarantines through the
    serial fallback in BOTH modes; sibling lanes still batch and the
    digests still match."""
    from open_simulator_tpu.campaign import CampaignOptions, run_campaign

    fleet = _write_fleet(tmp_path, poison_idx=1)
    serial = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=False, checkpoint=False))
    lanes = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=True, checkpoint=False))
    assert serial["totals"]["quarantined"] == 1
    assert lanes["digest"] == serial["digest"]
    assert lanes["launches"] == 2          # 1 batched + 1 serial quarantine
    code = lanes["quarantined"][0]["error"]["code"]
    assert code == "E_SOURCE"


def test_fleet_lane_poisoned_lane_is_isolated(tmp_path, monkeypatch):
    """PER-LANE quarantine: one lane of a batched launch failing its
    decode/audit quarantines ALONE — siblings from the same launch
    settle normally and the launch still counts once."""
    from open_simulator_tpu.campaign import (
        CampaignOptions,
        lanes as lanes_mod,
        run_campaign,
    )

    fleet = _write_fleet(tmp_path)
    real = lanes_mod._decode_lane

    def poisoned(prep, out, lane, n_lanes, opts, campaign_id):
        if prep.entry.name == "c2":
            raise SimulationError("placement audit violated (injected)",
                                  code="E_AUDIT", ref="cluster/c2")
        return real(prep, out, lane, n_lanes, opts, campaign_id)

    monkeypatch.setattr(lanes_mod, "_decode_lane", poisoned)
    rep = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=True, checkpoint=False))
    assert rep["totals"]["completed"] == 3
    assert rep["totals"]["quarantined"] == 1
    assert rep["quarantined"][0]["cluster"] == "c2"
    assert rep["quarantined"][0]["error"]["code"] == "E_AUDIT"
    assert rep["launches"] == 1            # the launch itself succeeded


def test_fleet_lane_mixed_buckets_group_by_shape(tmp_path):
    """Clusters in DIFFERENT shape buckets must not share a launch:
    two buckets -> two (or more) launches, each still batched."""
    from open_simulator_tpu.campaign import CampaignOptions, run_campaign
    from open_simulator_tpu.replay import synthetic_replay_cluster

    for i in range(2):
        cl = synthetic_replay_cluster(n_nodes=6, n_initial_pods=12)
        (tmp_path / f"small{i}.yaml").write_text(yaml.safe_dump_all(
            [{"apiVersion": "v1", "kind": "Node", **n.raw}
             for n in cl.nodes]
            + [{"apiVersion": "v1", "kind": "Pod", **p.raw}
               for p in cl.pods]))
    for i in range(2):
        cl = synthetic_replay_cluster(n_nodes=40, n_initial_pods=80)
        (tmp_path / f"big{i}.yaml").write_text(yaml.safe_dump_all(
            [{"apiVersion": "v1", "kind": "Node", **n.raw}
             for n in cl.nodes]
            + [{"apiVersion": "v1", "kind": "Pod", **p.raw}
               for p in cl.pods]))
    rep = run_campaign(CampaignOptions(
        fleet=str(tmp_path), fleet_lanes=True, checkpoint=False))
    assert rep["totals"]["completed"] == 4
    assert rep["launches"] == 2
    assert len(rep["buckets"]) == 2


def test_fleet_lane_width_caps_the_batch(tmp_path):
    from open_simulator_tpu.campaign import CampaignOptions, run_campaign

    fleet = _write_fleet(tmp_path)
    rep = run_campaign(CampaignOptions(
        fleet=fleet, fleet_lanes=True, lane_width=2, checkpoint=False))
    assert rep["totals"]["completed"] == 4
    assert rep["launches"] == 2
