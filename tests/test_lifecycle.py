"""Survivable serving: admission queue, deadlines/cancellation, sweep
checkpoint/resume, graceful drain (resilience/lifecycle.py + the reworked
server front end). The SIGKILL crash-recovery path has its own file
(test_resume_crash.py) — here the "crash" is a truncated journal."""

import json
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from open_simulator_tpu import telemetry
from open_simulator_tpu.resilience import lifecycle
from open_simulator_tpu.resilience.journal import unframe_line
from open_simulator_tpu.resilience.retry import backoff_delay, run_with_retries
from open_simulator_tpu.server.rest import SimulationServer, _make_handler


# ---- CancelToken ---------------------------------------------------------


def test_cancel_token_deadline_and_explicit():
    tok = lifecycle.CancelToken(deadline_s=60.0)
    assert not tok.cancelled
    assert 0 < tok.remaining() <= 60.0
    tok.cancel("client went away")
    assert tok.cancelled and tok.remaining() == 0.0
    err = tok.error("somewhere")
    assert err.code == "E_CANCELLED"
    assert "client went away" in err.message and "somewhere" in err.message

    expired = lifecycle.CancelToken(deadline_s=0.001)
    time.sleep(0.005)
    assert expired.cancelled
    err = expired.error(partial={"probed_counts": [0, 8]})
    assert err.code == "E_DEADLINE"
    assert err.to_dict()["partial"] == {"probed_counts": [0, 8]}
    with pytest.raises(lifecycle.CancelledError):
        expired.check("round boundary")

    # no deadline, never cancelled: free to run forever
    free = lifecycle.CancelToken()
    assert not free.cancelled and free.remaining() is None
    free.check()


def test_cancel_scope_threads_token_to_library_code():
    assert lifecycle.current_token() is None
    lifecycle.check_current("no scope")  # no-op outside a scope
    tok = lifecycle.CancelToken()
    with lifecycle.cancel_scope(tok):
        assert lifecycle.current_token() is tok
        lifecycle.check_current()
        tok.cancel()
        with pytest.raises(lifecycle.CancelledError) as ei:
            lifecycle.check_current("loop", partial=lambda: {"done": 3})
        assert ei.value.partial == {"done": 3}
    assert lifecycle.current_token() is None


# ---- retry satellite: jitter + elapsed cap -------------------------------


def test_backoff_delay_schedule_deterministic_and_jittered():
    # deterministic: exponential, capped
    assert [backoff_delay(a, 0.1, 0.5) for a in range(4)] == [
        0.1, 0.2, 0.4, 0.5]
    # full jitter: uniform in [0, capped], reproducible with a seeded rng
    rng = random.Random(7)
    draws = [backoff_delay(a, 0.1, 0.5, jitter=True, rng=rng)
             for a in range(50)]
    caps = [min(0.1 * 2.0 ** a, 0.5) for a in range(50)]
    assert all(0.0 <= d <= c for d, c in zip(draws, caps))
    assert len(set(draws)) > 10  # actually jittered, not constant
    # same seed, same schedule
    rng2 = random.Random(7)
    assert draws == [backoff_delay(a, 0.1, 0.5, jitter=True, rng=rng2)
                     for a in range(50)]


def test_run_with_retries_jitter_bounds_sleeps():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise RuntimeError("transient")
        return "ok"

    assert run_with_retries(flaky, retries=5, backoff_s=0.1,
                            jitter=True, rng=random.Random(3),
                            retry_on=(RuntimeError,),
                            sleep=sleeps.append) == "ok"
    assert len(sleeps) == 3
    for i, s in enumerate(sleeps):
        assert 0.0 <= s <= min(0.1 * 2.0 ** i, 2.0)


def test_run_with_retries_max_elapsed_caps_the_loop():
    """The next planned sleep would blow the wall-clock budget: stop
    retrying and re-raise even though attempts remain."""
    sleeps = []

    def always():
        raise RuntimeError("hard")

    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="hard"):
        run_with_retries(always, retries=50, backoff_s=0.2,
                         max_elapsed_s=0.1, retry_on=(RuntimeError,),
                         sleep=sleeps.append)
    # first planned sleep (0.2s) already exceeds the 0.1s budget
    assert sleeps == []
    assert time.monotonic() - t0 < 1.0

    # a budget that allows one backoff but not two
    sleeps2 = []
    with pytest.raises(RuntimeError):
        run_with_retries(always, retries=50, backoff_s=0.04,
                         max_backoff_s=10.0, max_elapsed_s=0.05,
                         retry_on=(RuntimeError,),
                         sleep=lambda s: (sleeps2.append(s), time.sleep(s)))
    assert len(sleeps2) == 1


# ---- AdmissionQueue ------------------------------------------------------


def test_queue_runs_jobs_in_order_and_sheds_when_full():
    q = lifecycle.AdmissionQueue(depth=2, initial_service_s=0.5)
    gate = threading.Event()
    order = []

    def make(i):
        def fn():
            gate.wait(2.0)
            order.append(i)
            return i
        return fn

    jobs = [q.submit(make(0))]          # starts executing, blocks on gate
    time.sleep(0.05)                    # let the worker pick job 0 up
    jobs += [q.submit(make(1)), q.submit(make(2))]  # fills depth-2 queue
    with pytest.raises(lifecycle.QueueFullError) as ei:
        q.submit(make(3))
    # backlog = 2 queued + 1 in flight; EWMA 0.5s -> ceil(0.5 * 4) = 2
    assert ei.value.retry_after_s >= 1.0
    assert ei.value.to_dict()["retry_after_s"] == ei.value.retry_after_s
    gate.set()
    for j in jobs:
        assert j.wait(2.0)
    assert order == [0, 1, 2] and [j.result for j in jobs] == [0, 1, 2]
    assert q.join(1.0)


def test_queue_skips_jobs_whose_deadline_lapsed_while_queued():
    q = lifecycle.AdmissionQueue(depth=4)
    gate = threading.Event()
    ran = []
    q.submit(lambda: gate.wait(2.0))
    time.sleep(0.05)
    dead = lifecycle.CancelToken()
    dead.cancel("deadline lapsed in queue")
    j_dead = q.submit(lambda: ran.append("dead"), token=dead)
    j_live = q.submit(lambda: ran.append("live") or "ok")
    gate.set()
    assert j_dead.wait(2.0) and j_live.wait(2.0)
    assert ran == ["live"]          # the cancelled job never executed
    assert j_dead.result is None and j_live.result == "ok"


def test_queue_worker_survives_poisoned_job():
    """A job whose fn raises must not kill the singleton worker: the
    exception lands on job.error, jobs queued behind it still run."""
    q = lifecycle.AdmissionQueue(depth=4)

    class Rude(BaseException):
        pass

    def poison():
        raise Rude("boom")

    j_bad = q.submit(poison)
    j_ok = q.submit(lambda: "fine")
    assert j_bad.wait(2.0) and j_ok.wait(2.0)
    assert isinstance(j_bad.error, Rude) and j_bad.result is None
    assert j_ok.error is None and j_ok.result == "fine"
    assert q.join(1.0)


def test_sweep_journal_prune_keeps_unfinished(tmp_path):
    """prune: completed journals past the keep cap go oldest-first;
    unfinished journals (resumable crash evidence) always stay."""
    fp = {"engine": "e", "bucket": [4, 8], "workload": "w"}
    ids = []
    for i in range(4):
        j = lifecycle.SweepJournal.create(str(tmp_path), fp, 4, 2,
                                          (100.0, 100.0, 100.0))
        if i != 2:                       # journal 2 stays unfinished
            j.finish(1, f"d{i}")
        ids.append(j.sweep_id)
        import os as _os
        _os.utime(j.path, (1000.0 + i, 1000.0 + i))
    removed = lifecycle.SweepJournal.prune(str(tmp_path), keep=1)
    assert removed == 2                  # journals 0 and 1 (oldest done)
    left = {p.name.split(".")[0] for p in tmp_path.iterdir()}
    assert left == {ids[2], ids[3]}      # unfinished + newest done


def test_journal_keep_env_resolution(monkeypatch):
    """Kind-specific override wins; an UNPARSABLE override falls through
    to the shared setting (not the default — the operator's disk bound
    must not silently 8x because of a typo in the specific env)."""
    monkeypatch.delenv(lifecycle.JOURNAL_KEEP_ENV, raising=False)
    monkeypatch.delenv(lifecycle.SHARED_JOURNAL_KEEP_ENV, raising=False)
    assert lifecycle.journal_keep(
        lifecycle.JOURNAL_KEEP_ENV) == lifecycle.DEFAULT_JOURNAL_KEEP
    monkeypatch.setenv(lifecycle.SHARED_JOURNAL_KEEP_ENV, "4")
    assert lifecycle.journal_keep(lifecycle.JOURNAL_KEEP_ENV) == 4
    monkeypatch.setenv(lifecycle.JOURNAL_KEEP_ENV, "7")
    assert lifecycle.journal_keep(lifecycle.JOURNAL_KEEP_ENV) == 7
    monkeypatch.setenv(lifecycle.JOURNAL_KEEP_ENV, "n/a")
    assert lifecycle.journal_keep(lifecycle.JOURNAL_KEEP_ENV) == 4


def test_keyed_mutex_try_hold_nonblocking():
    """try_hold: the session store's eviction path must never block on
    another key's lock (AB-BA deadlock with a thread evicting the other
    way); it yields False while the key is held elsewhere and True with
    the lock once it is free."""
    m = lifecycle.KeyedMutex()
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with m.hold("a"):
            acquired.set()
            release.wait(5.0)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert acquired.wait(5.0)
    with m.try_hold("a") as got:
        assert not got                   # held by the other thread
    with m.try_hold("b") as got:
        assert got                       # free key: taken
    release.set()
    t.join(5.0)
    with m.try_hold("a") as got:
        assert got                       # free again
    assert m._locks == {}                # refcounted cleanup ran


def test_queue_close_rejects_and_join_waits():
    q = lifecycle.AdmissionQueue(depth=4)
    done = []
    q.submit(lambda: (time.sleep(0.1), done.append(1)))
    q.close()
    with pytest.raises(lifecycle.QueueClosedError):
        q.submit(lambda: None)
    assert q.join(2.0)              # in-flight work finished the drain
    assert done == [1]
    assert q.stats()["closed"] and q.stats()["in_flight"] == 0


# ---- SweepJournal --------------------------------------------------------


def _journal_roundtrip_dir(tmp_path):
    fp = {"engine": "e0", "bucket": [8, 16], "workload": "w0"}
    j = lifecycle.SweepJournal.create(str(tmp_path), fp, max_new=8, lanes=4,
                                      thresholds=(100.0, 100.0, 100.0))
    j.append_round([0, 1, 8], {
        0: {"nodes": [0, -1], "gpu": None, "vol": None, "error": None,
            "stats": [False, 50.0, 25.0, False]},
        1: {"nodes": [0, 1], "gpu": None, "vol": None, "error": None,
            "stats": [True, 40.0, 20.0, True]},
        8: {"nodes": [0, 1], "gpu": None, "vol": None, "error": None,
            "stats": [True, 10.0, 5.0, True]},
    })
    return fp, j


def test_sweep_journal_roundtrip_prefix_and_last(tmp_path):
    fp, j = _journal_roundtrip_dir(tmp_path)
    j.finish(1, "abcd")
    loaded = lifecycle.SweepJournal.load(str(tmp_path), j.sweep_id[:6])
    assert loaded.sweep_id == j.sweep_id
    assert loaded.done["best_count"] == 1 and loaded.done["digest"] == "abcd"
    lanes = loaded.recorded_lanes()
    assert sorted(lanes) == [0, 1, 8]
    assert lanes[1]["stats"] == [True, 40.0, 20.0, True]
    loaded.verify(fp, 8, 4, (100.0, 100.0, 100.0))
    assert lifecycle.SweepJournal.load(str(tmp_path), "last").sweep_id == j.sweep_id


def test_sweep_journal_verify_rejects_drift(tmp_path):
    fp, j = _journal_roundtrip_dir(tmp_path)
    loaded = lifecycle.SweepJournal.load(str(tmp_path), j.sweep_id)
    with pytest.raises(lifecycle.ResumeError, match="fingerprint drifted"):
        loaded.verify({**fp, "workload": "CHANGED"}, 8, 4,
                      (100.0, 100.0, 100.0))
    with pytest.raises(lifecycle.ResumeError, match="max_new 8 -> 16"):
        loaded.verify(fp, 16, 4, (100.0, 100.0, 100.0))
    with pytest.raises(lifecycle.ResumeError, match="thresholds changed"):
        loaded.verify(fp, 8, 4, (90.0, 100.0, 100.0))
    with pytest.raises(lifecycle.ResumeError, match="no sweep checkpoint "
                                                    "matches"):
        lifecycle.SweepJournal.load(str(tmp_path), "zzzzzz")


def test_sweep_journal_drops_torn_trailing_line(tmp_path):
    fp, j = _journal_roundtrip_dir(tmp_path)
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"kind": "round", "round": 2, "counts": [4], "la')  # torn
    loaded = lifecycle.SweepJournal.load(str(tmp_path), j.sweep_id)
    assert len(loaded.rounds) == 1 and loaded.done is None


# ---- bisect checkpoint/resume + cancellation -----------------------------


def _snapshot(n_pods=12, pod_cpu="1500m", max_new=8):
    from open_simulator_tpu.core import AppResource, build_pod_sequence
    from open_simulator_tpu.encode.snapshot import EncodeOptions, encode_cluster
    from open_simulator_tpu.k8s.loader import ClusterResources, make_valid_node
    from tests.conftest import make_node, make_pod

    cluster = ClusterResources()
    cluster.nodes = [make_node("real-0", cpu_m=4000, mem_mib=8192)]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu=pod_cpu, mem="512Mi")
                for i in range(n_pods)]
    pods = build_pod_sequence(
        cluster, [AppResource(name="a", resources=app)])
    template = make_node("template", cpu_m=4000, mem_mib=8192)
    return encode_cluster(
        [make_valid_node(n) for n in cluster.nodes], pods,
        EncodeOptions(max_new_nodes=max_new, new_node_template=template))


def test_bisect_checkpoints_and_resumes_identically(tmp_path, monkeypatch):
    """In-process crash sim: run with checkpointing, truncate the journal
    to its first round ("crash"), resume — the resumed plan's digest must
    equal the uninterrupted run's, with fewer executed rounds."""
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel.sweep import capacity_bisect
    from open_simulator_tpu.telemetry.ledger import plan_digest

    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    snap = _snapshot()
    cfg = make_config(snap)
    plan = capacity_bisect(snap, cfg, 8, lanes=2)
    assert plan.best_count == 5          # 12 pods x 1500m, 2 per node
    assert plan.sweep_id and plan.resumed_rounds == 0
    full = lifecycle.SweepJournal.load(str(tmp_path), plan.sweep_id)
    assert len(full.rounds) >= 2 and full.done["best_count"] == 5
    assert full.done["digest"] == plan_digest(plan)["digest"]

    # "crash" after round 1: drop every later line
    with open(full.path, "r", encoding="utf-8") as f:
        lines = f.readlines()
    kept = [ln for ln in lines
            if json.loads(unframe_line(ln)).get("kind") == "header"
            or json.loads(unframe_line(ln)).get("round") == 1]
    with open(full.path, "w", encoding="utf-8") as f:
        f.writelines(kept)

    resumed = capacity_bisect(snap, cfg, 8, lanes=2, resume=plan.sweep_id)
    assert resumed.resumed_rounds == 1
    assert resumed.best_count == plan.best_count
    assert resumed.counts == plan.counts
    assert plan_digest(resumed)["digest"] == plan_digest(plan)["digest"]
    np.testing.assert_array_equal(resumed.nodes_per_scenario,
                                  plan.nodes_per_scenario)

    # resuming the COMPLETE journal executes nothing and still agrees
    replay = capacity_bisect(snap, cfg, 8, lanes=2, resume=plan.sweep_id)
    assert plan_digest(replay)["digest"] == plan_digest(plan)["digest"]


def test_bisect_resume_rejects_workload_drift(tmp_path, monkeypatch):
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel.sweep import capacity_bisect

    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    snap = _snapshot()
    plan = capacity_bisect(snap, make_config(snap), 8, lanes=2)
    other = _snapshot(n_pods=10)         # different workload, same shapes
    with pytest.raises(lifecycle.ResumeError, match="fingerprint drifted"):
        capacity_bisect(other, make_config(other), 8, lanes=2,
                        resume=plan.sweep_id)


def test_bisect_disabled_checkpointing_writes_nothing(tmp_path, monkeypatch):
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel.sweep import capacity_bisect

    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    monkeypatch.setenv("SIMON_SWEEP_CHECKPOINT", "0")
    snap = _snapshot()
    plan = capacity_bisect(snap, make_config(snap), 8, lanes=2)
    assert plan.sweep_id is None
    assert not list(tmp_path.iterdir())


def test_bisect_observes_cancellation_at_round_boundary():
    """A token cancelled after the first round stops the bisection at the
    next boundary with partial results (probed counts, best so far)."""
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.parallel.sweep import capacity_bisect

    class CountdownToken(lifecycle.CancelToken):
        """Cancelled from the Nth .cancelled query on (deadline-style)."""

        def __init__(self, allow_checks: int):
            super().__init__()
            self.allow = allow_checks

        @property
        def cancelled(self):
            if self.allow > 0:
                self.allow -= 1
                return False
            return True

    snap = _snapshot()
    cfg = make_config(snap)
    tok = CountdownToken(allow_checks=1)   # round 1 runs; round 2 cancels
    with lifecycle.cancel_scope(tok):
        with pytest.raises(lifecycle.CancelledError) as ei:
            capacity_bisect(snap, cfg, 8, lanes=2, checkpoint=False)
    partial = ei.value.partial
    assert partial["probed_counts"]        # round 1's ladder landed
    assert set(partial["probed_counts"]) < set(range(9))
    assert ei.value.code == "E_DEADLINE"


# ---- server: 429/Retry-After, soak, orphan fix, drain --------------------


CLUSTER_YAML = """
apiVersion: v1
kind: Node
metadata: {name: s0}
status:
  allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
"""


def _mini_server(depth=1, request_timeout_s=300.0, drain_timeout_s=5.0):
    srv = SimulationServer(queue_depth=depth,
                           request_timeout_s=request_timeout_s,
                           drain_timeout_s=drain_timeout_s)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post_status(url, payload):
    """POST returning (status, headers, body) without raising."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_server_soak_queue_backpressure_and_no_deadlock():
    """Threaded soak: POSTs queue/shed while GETs scrape concurrently.
    Full queue -> 429 with a Retry-After header; nothing deadlocks; the
    shed counter moves exactly once per 429; depth/in-flight return to 0."""
    srv, httpd, url = _mini_server(depth=1)
    srv.deploy_apps = lambda body: (time.sleep(0.25), {"ok": True})[1]
    shed0 = telemetry.counter("simon_queue_shed_total").value()
    wait_h = telemetry.REGISTRY.histogram("simon_queue_wait_seconds")
    waits0, _ = wait_h.child_stats()

    results = []
    res_lock = threading.Lock()
    barrier = threading.Barrier(8)

    def post():
        barrier.wait(5.0)
        out = _post_status(url + "/api/deploy-apps", {"apps": []})
        with res_lock:
            results.append(out)

    get_errors = []

    def scrape():
        barrier.wait(5.0)
        for _ in range(10):
            try:
                with urllib.request.urlopen(url + "/metrics") as r:
                    assert b"simon_queue_depth" in r.read()
                with urllib.request.urlopen(url + "/api/runs") as r:
                    json.loads(r.read())
            except Exception as e:  # noqa: BLE001
                get_errors.append(e)

    threads = [threading.Thread(target=post) for _ in range(6)] + \
              [threading.Thread(target=scrape) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(15.0)
            assert not t.is_alive(), "soak deadlocked"
        assert not get_errors, get_errors
        statuses = sorted(s for s, _, _ in results)
        assert len(statuses) == 6 and set(statuses) <= {200, 429}
        n429 = statuses.count(429)
        assert statuses.count(200) >= 1 and n429 >= 1
        for status, headers, body in results:
            if status == 429:
                assert int(headers["Retry-After"]) >= 1
                assert body["code"] == "E_OVERLOADED"
                assert body["retry_after_s"] >= 1.0
        # monotone queue metrics: one shed per 429, one wait observation
        # per executed job, gauges back to 0
        assert telemetry.counter("simon_queue_shed_total").value() - shed0 \
            == n429
        waits1, _ = wait_h.child_stats()
        assert waits1 - waits0 == statuses.count(200)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and (
                telemetry.gauge("simon_queue_depth").value() != 0
                or telemetry.gauge("simon_queue_in_flight").value() != 0):
            time.sleep(0.02)
        assert telemetry.gauge("simon_queue_depth").value() == 0
        assert telemetry.gauge("simon_queue_in_flight").value() == 0
    finally:
        httpd.shutdown()


def test_504_cancels_worker_no_orphan():
    """The PR-1 regression: the old timeout path left the worker thread
    burning the device. Now the 504 cancels the token; a cooperative
    handler stops at its next boundary and the in-flight gauge returns
    to 0 within one 'round'."""
    srv, httpd, url = _mini_server(depth=2, request_timeout_s=0.15)

    def cooperative(body):
        while True:                       # a sweep-round-like loop
            lifecycle.check_current("test round boundary")
            time.sleep(0.01)

    srv.deploy_apps = cooperative
    try:
        status, _, body = _post_status(url + "/api/deploy-apps", {"apps": []})
        assert status == 504
        assert body["code"] == "E_DEADLINE"
        # the worker observed the cancellation: in-flight drains to 0
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and \
                telemetry.gauge("simon_queue_in_flight").value() != 0:
            time.sleep(0.02)
        assert telemetry.gauge("simon_queue_in_flight").value() == 0
        # and the queue is alive for the next request
        srv.deploy_apps = lambda b: {"ok": True}
        status, _, body = _post_status(url + "/api/deploy-apps", {"apps": []})
        assert status == 200 and body == {"ok": True}
    finally:
        httpd.shutdown()


def test_non_object_json_body_is_structured_400():
    """Valid JSON that is not an object (42, [], \"x\") must get a
    structured 400, not an AttributeError-killed connection."""
    srv, httpd, url = _mini_server(depth=2)
    try:
        for raw in (b"42", b"[]", b'"zap"'):
            req = urllib.request.Request(
                url + "/api/deploy-apps", data=raw,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
            body = json.loads(ei.value.read())
            assert body["code"] == "E_BAD_REQUEST"
            assert "JSON object" in body["error"]
    finally:
        httpd.shutdown()


def test_drain_timeout_cancels_queued_jobs_too():
    """Past --drain-timeout, QUEUED jobs are cancelled as well: the
    worker must not start fresh device work during shutdown, and the
    queued clients get a structured 504 rather than a connection reset."""
    srv, httpd, url = _mini_server(depth=4, drain_timeout_s=0.2)
    started = []

    def cooperative(body):
        started.append(1)
        while True:
            lifecycle.check_current("slow loop")
            time.sleep(0.01)

    srv.deploy_apps = cooperative
    outs = {}

    def post(i):
        outs[i] = _post_status(url + "/api/deploy-apps", {"apps": []})

    threads = [threading.Thread(target=post, args=(i,)) for i in range(3)]
    try:
        threads[0].start()
        time.sleep(0.1)                 # job 0 executing
        threads[1].start()
        threads[2].start()
        time.sleep(0.1)                 # jobs 1, 2 queued
        info = srv.begin_drain()
        assert info["drained_clean"] is True
        for t in threads:
            t.join(5.0)
            assert not t.is_alive()
        assert len(started) == 1        # queued jobs never executed
        assert outs[0][0] == 504        # in-flight: cancelled at boundary
        for i in (1, 2):
            status, _, body = outs[i]
            assert status == 504 and body["code"] == "E_CANCELLED"
            assert "draining" in body["error"]
    finally:
        httpd.shutdown()


def test_client_deadline_s_validated_and_enforced():
    srv, httpd, url = _mini_server(depth=2)
    srv.deploy_apps = lambda body: (time.sleep(0.5), {"ok": True})[1]
    try:
        status, _, body = _post_status(
            url + "/api/deploy-apps", {"deadline_s": "soon"})
        assert status == 400 and body["field"] == "deadline_s"
        status, _, body = _post_status(
            url + "/api/deploy-apps", {"deadline_s": -3})
        assert status == 400 and body["field"] == "deadline_s"
        # a client deadline tighter than --request-timeout wins
        status, _, body = _post_status(
            url + "/api/deploy-apps", {"deadline_s": 0.05})
        assert status == 504 and body["code"] == "E_DEADLINE"
    finally:
        httpd.shutdown()


def test_graceful_drain_finishes_inflight_rejects_new(tmp_path, monkeypatch):
    """begin_drain: readyz flips (healthz does not), the in-flight request
    completes, new POSTs bounce with 503 E_BUSY, and the final ledger
    record lands."""
    from open_simulator_tpu.telemetry import ledger

    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(str(tmp_path))
    srv, httpd, url = _mini_server(depth=2, drain_timeout_s=5.0)
    release = threading.Event()

    def slow(body):
        release.wait(5.0)
        return {"finished": True}

    srv.deploy_apps = slow
    inflight = {}

    def post_inflight():
        inflight["out"] = _post_status(url + "/api/deploy-apps", {"apps": []})

    t = threading.Thread(target=post_inflight)
    drain_info = {}
    try:
        t.start()
        time.sleep(0.1)                   # the slow POST is executing
        drainer = threading.Thread(
            target=lambda: drain_info.update(srv.begin_drain()))
        drainer.start()
        time.sleep(0.1)                   # drain has begun, work in flight
        # readyz flipped BEFORE healthz ever would (healthz never flips)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/readyz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read()) == {"ready": False,
                                               "draining": True}
        with urllib.request.urlopen(url + "/healthz") as r:
            hz = json.loads(r.read())
        assert hz["status"] == "healthy" and hz["draining"] is True
        # new work is rejected with the draining busy-503
        status, _, body = _post_status(url + "/api/deploy-apps", {"apps": []})
        assert status == 503 and body["code"] == "E_BUSY"
        assert "draining" in body["error"]
        # the held request still completes
        release.set()
        t.join(5.0)
        drainer.join(5.0)
        assert inflight["out"][0] == 200
        assert inflight["out"][2] == {"finished": True}
        assert drain_info["drained_clean"] is True
        [rec] = [r for r in ledger.default_ledger().records()
                 if r["surface"] == "server:drain"]
        assert rec["run_id"] == drain_info["ledger_run_id"]
        assert rec["tags"]["drained_clean"] is True
    finally:
        ledger.configure(None)
        httpd.shutdown()


def test_fault_mid_drain_answers_structured_and_drains_clean(
        tmp_path, monkeypatch):
    """ISSUE-14 satellite: a deterministic device fault on the in-flight
    request DURING a SIGTERM drain must answer its structured 5xx (never
    a bare traceback), and the drain still finishes clean with its
    ledger record — a bad device cannot turn shutdown into a crash."""
    from open_simulator_tpu.resilience import faults
    from open_simulator_tpu.server import serving
    from open_simulator_tpu.telemetry import ledger

    monkeypatch.delenv(ledger.LEDGER_DIR_ENV, raising=False)
    ledger.configure(str(tmp_path))
    srv, httpd, url = _mini_server(depth=2, drain_timeout_s=10.0)
    entered, release = threading.Event(), threading.Event()
    real_launch = serving._launch_group

    def gated(members):
        # hold the launch open until the drain has begun, so the fault
        # genuinely fires mid-drain
        entered.set()
        release.wait(10.0)
        return real_launch(members)

    monkeypatch.setattr(serving, "_launch_group", gated)
    inflight = {}

    def post():
        inflight["out"] = _post_status(
            url + "/api/simulate", {"cluster": {"yaml": CLUSTER_YAML}})

    t = threading.Thread(target=post)
    drain_info = {}
    drainer = threading.Thread(
        target=lambda: drain_info.update(srv.begin_drain()))
    try:
        # E_COMPILE is deterministic and has no serving rung for a
        # singleton member: the request must answer the structured 500
        with faults.injected("fn=serving_lanes,exc=compile,times=99"):
            t.start()
            assert entered.wait(10.0)
            drainer.start()
            time.sleep(0.1)            # drain underway, launch held
            release.set()
            t.join(15.0)
            drainer.join(15.0)
        status, _, body = inflight["out"]
        assert status == 500 and body["code"] == "E_COMPILE", (status, body)
        assert "compilation" in body["error"]
        assert drain_info.get("drained_clean") is True
        [rec] = [r for r in ledger.default_ledger().records()
                 if r["surface"] == "server:drain"]
        assert rec["tags"]["drained_clean"] is True
    finally:
        ledger.configure(None)
        httpd.shutdown()


def test_drain_timeout_cancels_stuck_inflight():
    """Work that outlives --drain-timeout is cancelled cooperatively: the
    drain still converges instead of hanging shutdown forever."""
    srv, httpd, url = _mini_server(depth=2, drain_timeout_s=0.2)

    def stuck_but_cooperative(body):
        while True:
            lifecycle.check_current("stuck loop")
            time.sleep(0.01)

    srv.deploy_apps = stuck_but_cooperative
    try:
        t = threading.Thread(target=lambda: _post_status(
            url + "/api/deploy-apps", {"apps": []}))
        t.start()
        time.sleep(0.1)
        info = srv.begin_drain()
        assert info["drained_clean"] is True   # cancellation converged it
        t.join(5.0)
        assert not t.is_alive()
    finally:
        httpd.shutdown()


def test_capacity_endpoint_checkpoints_and_resumes(tmp_path, monkeypatch):
    """POST /api/capacity returns a sweep_id when checkpointing is on;
    posting again with resume replays the recorded rounds and agrees."""
    srv, httpd, url = _mini_server(depth=2)
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    node_spec = ("apiVersion: v1\nkind: Node\nmetadata: {name: template}\n"
                 "status:\n  allocatable: {cpu: '8', memory: 16Gi, "
                 "pods: '110'}\n")
    app_yaml = """
apiVersion: apps/v1
kind: Deployment
metadata: {name: a, namespace: default}
spec:
  replicas: 40
  selector: {matchLabels: {app: a}}
  template:
    metadata: {labels: {app: a}}
    spec:
      containers:
        - name: c
          resources: {requests: {cpu: "2", memory: 2Gi}}
"""
    body = {"cluster": {"yaml": CLUSTER_YAML},
            "apps": [{"name": "a", "yaml": app_yaml}],
            "new_node": {"spec_yaml": node_spec},
            "max_new_nodes": 16}
    try:
        s1, _, out1 = _post_status(url + "/api/capacity", body)
        assert s1 == 200 and out1["sweep_id"] and out1["resumed_rounds"] == 0
        s2, _, out2 = _post_status(
            url + "/api/capacity", {**body, "resume": out1["sweep_id"]})
        assert s2 == 200
        assert out2["best_count"] == out1["best_count"]
        assert out2["counts"] == out1["counts"]
        assert out2["resumed_rounds"] >= 1
        # drifted request (different max_new) -> structured 409
        s3, _, out3 = _post_status(
            url + "/api/capacity",
            {**body, "max_new_nodes": 8, "resume": out1["sweep_id"]})
        assert s3 == 409 and out3["code"] == "E_RESUME"
        # resume only exists for bisect
        s4, _, out4 = _post_status(
            url + "/api/capacity",
            {**body, "sweep_mode": "exhaustive", "resume": out1["sweep_id"]})
        assert s4 == 400 and out4["field"] == "resume"
    finally:
        httpd.shutdown()


# ---- drain with open digital-twin sessions (ISSUE 11 satellite) ----------


TWIN_CLUSTER_YAML = """
apiVersion: v1
kind: Node
metadata: {name: s0}
status:
  allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
---
apiVersion: v1
kind: Node
metadata: {name: s1}
status:
  allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
"""


def test_drain_with_open_sessions_journals_and_resumes(tmp_path,
                                                       monkeypatch):
    """SIGTERM (begin_drain) with an in-flight /events POST: the step
    FINISHES and lands in the session journal, readyz flips while
    healthz stays 200, new events bounce E_BUSY, the drain reports the
    flushed sessions — and a restarted server serves the session with
    the drained-through digest intact."""
    from open_simulator_tpu.replay import session as sess_mod

    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    srv, httpd, url = _mini_server(depth=2, drain_timeout_s=10.0)
    try:
        status, _, created = _post_status(
            url + "/api/session",
            {"cluster": {"yaml": TWIN_CLUSTER_YAML}, "name": "drainme"})
        assert status == 200, created
        sid = created["session_id"]

        real_settle = sess_mod.settle_step
        started, release = threading.Event(), threading.Event()

        def slow_settle(*a, **kw):
            started.set()
            release.wait(10.0)
            return real_settle(*a, **kw)

        monkeypatch.setattr(sess_mod, "settle_step", slow_settle)
        inflight = {}

        def post_events():
            inflight["out"] = _post_status(
                url + f"/api/session/{sid}/events",
                {"events": [{"t": 1, "kind": "kill_node", "target": "s0"}]})

        t = threading.Thread(target=post_events)
        t.start()
        assert started.wait(10.0), "events POST never reached the worker"
        drain_info = {}
        drainer = threading.Thread(
            target=lambda: drain_info.update(srv.begin_drain()))
        drainer.start()
        deadline = time.time() + 5
        flipped = None
        while time.time() < deadline:
            try:
                urllib.request.urlopen(url + "/readyz")
            except urllib.error.HTTPError as e:
                flipped = (e.code, json.loads(e.read()))
                break
            time.sleep(0.05)
        assert flipped == (503, {"ready": False, "draining": True}), flipped
        with urllib.request.urlopen(url + "/healthz") as r:
            hz = json.loads(r.read())
        assert hz["status"] == "healthy" and hz["draining"] is True
        status, _, body = _post_status(
            url + f"/api/session/{sid}/events",
            {"events": [{"t": 2, "kind": "kill_node", "target": "s1"}]})
        assert status == 503 and body["code"] == "E_BUSY", (status, body)
        release.set()
        t.join(15.0)
        drainer.join(15.0)
        assert not t.is_alive() and not drainer.is_alive()
        # the in-flight step FINISHED the drain (not cancelled)
        assert inflight["out"][0] == 200, inflight["out"]
        digest = inflight["out"][2]["digest"]
        assert inflight["out"][2]["status"]["steps"] == 2
        assert drain_info["drained_clean"] is True
        assert drain_info["open_sessions"] == 1
        assert drain_info["flushed"] == 1
        monkeypatch.setattr(sess_mod, "settle_step", real_settle)
        # every settled step is on disk: header + baseline + the event
        jpath = tmp_path / (sid + sess_mod.SESSION_JOURNAL_SUFFIX)
        with open(jpath, encoding="utf-8") as f:
            kinds = [json.loads(unframe_line(ln))["kind"] for ln in f]
        assert kinds == ["header", "step", "step"]
        # "restart": a fresh server over the same checkpoint dir serves
        # the session bit-identically and keeps settling events
        srv2 = SimulationServer()
        out = srv2.session_status(sid, {})
        assert out["digest"] == digest and out["steps"] == 2
        more = srv2.session_events(sid, {"events": [
            {"t": 2, "kind": "kill_node", "target": "s1"}]})
        assert more["status"]["steps"] == 3
    finally:
        httpd.shutdown()
