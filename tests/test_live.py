"""Live operations telemetry: the devmem ledger, the streaming event
feed, and the launch histogram (telemetry/live.py, ARCHITECTURE.md
section 21).

Covers:
* DeviceMemLedger upsert/release semantics, per-owner totals and
  high-watermarks, in-flight launch accounting via the estimator hook;
* reconcile(): a deliberately planted UNREGISTERED device array trips
  the leak flag; registering it clears the flag;
* the event feed: bounded per-subscriber queues where a slow consumer
  drops (counted) and never blocks publish; listener attach/detach on
  the black-box ring; close_all ends every subscriber;
* black-box listener fan-out outside the ring lock (exceptions
  swallowed), tail(), resize() keeping the newest events;
* configure_ring: flag/env validation into a structured E_SPEC error;
* telemetry/runtime.py device-memory gauge, BOTH branches: allocator
  memory_stats where the backend has them, summed live-array nbytes
  (stat=live_nbytes) where it does not;
* faults.run_launch observing simon_launch_seconds and witnessing the
  in-flight entry only for the launch's duration;
* multi-worker HTTP: concurrent traced launches on workers=2 land in
  the histogram and the devmem/debug sections without clobbering; the
  SSE stream (/api/events) shows the same causal kinds the
  /api/trace/<id> timeline reconstructs, and drain closes followers.
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

from http.server import ThreadingHTTPServer

import jax
import jax.numpy as jnp

from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.resilience import faults
from open_simulator_tpu.telemetry import context, live
from open_simulator_tpu.telemetry import runtime as tel_runtime


# ---- the devmem ledger ----------------------------------------------------


def test_ledger_register_release_totals_and_peaks():
    led = live.DeviceMemLedger()
    led.register("sessions", "s1", 100)
    led.register("sessions", "s2", 50)
    led.register("executables", "e1", 7)
    assert led.totals() == {"sessions": 150, "executables": 7}
    assert led.total() == 157
    # upsert replaces, never double-counts
    led.register("sessions", "s1", 10)
    assert led.totals()["sessions"] == 60
    # peaks remember the high-watermark, not the current value
    assert led.peaks()["sessions"] == 150
    assert led.peak_total() == 157
    assert led.release("sessions", "s2") == 50
    assert led.release("sessions", "nope") == 0
    assert led.release_owner("sessions") == 10
    assert led.totals() == {"executables": 7}
    st = led.stats()
    assert st["total"] == 7 and st["peak_total"] == 157
    assert st["inflight"] == []
    led.reset()
    assert led.total() == 0 and led.peak_total() == 0


def test_ledger_negative_bytes_clamped():
    led = live.DeviceMemLedger()
    assert led.register("sessions", "s", -5) == 0
    assert led.total() == 0


def test_inflight_uses_estimator_and_releases():
    led = live.DeviceMemLedger()
    led.set_inflight_estimator(
        lambda fn: 4096 if fn == "batched_schedule" else None)
    with led.inflight("batched_schedule"):
        assert led.totals()[live.OWNER_INFLIGHT] == 4096
        rows = led.inflight_entries()
        assert len(rows) == 1
        assert rows[0]["fn"] == "batched_schedule"
        assert rows[0]["age_ms"] >= 0
    assert led.totals().get(live.OWNER_INFLIGHT, 0) == 0
    assert led.inflight_entries() == []
    # explicit bytes beat the estimator; a broken estimator is harmless
    with led.inflight("batched_schedule", nbytes=8):
        assert led.totals()[live.OWNER_INFLIGHT] == 8
    led.set_inflight_estimator(lambda fn: 1 / 0)
    with led.inflight("other"):
        assert led.totals()[live.OWNER_INFLIGHT] == 0
    assert led.peaks()[live.OWNER_INFLIGHT] == 4096


def test_reconcile_flags_planted_unregistered_array():
    led = live.DeviceMemLedger()
    baseline = led.reconcile()["unattributed_bytes"]
    plant = jnp.zeros((2 * 1024 * 1024,), dtype=jnp.float32)  # 8 MiB
    plant.block_until_ready()
    tol = baseline + (4 << 20)
    r = led.reconcile(tolerance_bytes=tol)
    # the planted array is live but NOBODY registered it: leak
    assert r["unattributed_bytes"] >= baseline + (8 << 20) - (1 << 20)
    assert r["leak_suspected"], r
    assert r["live_arrays"] >= 1 and r["live_bytes_by_device"]
    # owning up clears the flag at the same tolerance
    led.register(live.OWNER_SESSIONS, "plant", int(plant.nbytes))
    r2 = led.reconcile(tolerance_bytes=tol)
    assert not r2["leak_suspected"], r2
    assert r2["registered_bytes"] >= 8 << 20
    del plant


def test_module_ledger_gauges_render_on_registry():
    from open_simulator_tpu.telemetry import registry
    live.DEVMEM.register(live.OWNER_EXECUTABLES, "test-gauge-probe", 123)
    try:
        text = registry.REGISTRY.render_prometheus()
        assert 'simon_devmem_bytes{owner="executables"}' in text
        assert 'simon_devmem_peak_bytes{owner="executables"}' in text
    finally:
        live.DEVMEM.release(live.OWNER_EXECUTABLES, "test-gauge-probe")


# ---- the event feed -------------------------------------------------------


def test_feed_slow_subscriber_drops_never_blocks():
    feed = live.EventFeed()
    fast = feed.subscribe(maxsize=64)
    slow = feed.subscribe(maxsize=1)
    try:
        t0 = time.perf_counter()
        for i in range(10):
            feed.publish({"kind": "launch", "seq": i})
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.5  # publish never blocked on the full queue
        assert slow.dropped == 9
        got = [fast.get(timeout=1.0)["seq"] for _ in range(10)]
        assert got == list(range(10))  # the healthy subscriber saw all
        assert slow.get(timeout=1.0)["seq"] == 0  # oldest kept, rest lost
        st = feed.stats()
        assert st["subscribers"] == 2
        assert st["subscriber_dropped"] == 9
    finally:
        feed.unsubscribe(fast)
        feed.unsubscribe(slow)
    assert feed.stats()["subscribers"] == 0


def test_feed_attaches_listener_only_while_subscribed():
    feed = live.EventFeed()
    box = context.BLACKBOX
    base = len(box._listeners)
    sub = feed.subscribe()
    assert len(box._listeners) == base + 1
    sub2 = feed.subscribe()
    assert len(box._listeners) == base + 1  # one listener, many subs
    feed.unsubscribe(sub)
    assert len(box._listeners) == base + 1
    feed.unsubscribe(sub2)
    assert len(box._listeners) == base  # last one out detaches
    # a ring record while subscribed lands in the queue, trace included
    sub3 = feed.subscribe()
    try:
        with context.trace_scope("feed-live-1"):
            box.record("launch", fn="x")
        ev = sub3.get(timeout=2.0)
        assert ev["kind"] == "launch" and "feed-live-1" in ev["traces"]
    finally:
        feed.unsubscribe(sub3)


def test_feed_close_all_ends_subscribers():
    feed = live.EventFeed()
    subs = [feed.subscribe() for _ in range(3)]
    feed.close_all()
    for s in subs:
        assert s.closed.is_set()
        assert s.get(timeout=0.2) is None  # the wake-up sentinel
    assert feed.stats()["subscribers"] == 0
    # closing is idempotent and publish-after-close is a no-op
    feed.publish({"kind": "launch"})
    feed.close_all()


def test_blackbox_listener_exceptions_swallowed():
    box = context.BlackBox(maxlen=8)
    seen = []

    def bad(ev):
        raise RuntimeError("listener bug")

    def good(ev):
        seen.append(ev["kind"])

    box.add_listener(bad)
    box.add_listener(good)
    box.add_listener(good)  # dedup: registered once
    box.record("enqueue")
    box.record("launch")
    assert seen == ["enqueue", "launch"]
    assert box.stats()["events"] == 2  # the ring recorded despite `bad`
    box.remove_listener(bad)
    box.remove_listener(good)
    box.remove_listener(good)  # second remove is a no-op
    box.record("response")
    assert seen == ["enqueue", "launch"]


def test_blackbox_tail_and_resize_keep_newest():
    box = context.BlackBox(maxlen=8)
    for i in range(6):
        box.record("enqueue", seq=i)
    tail = box.tail(3)
    assert [e["seq"] for e in tail] == [3, 4, 5]  # oldest-first window
    assert box.tail(0) == []
    tail[0]["seq"] = 99  # copies: mutating the tail never edits the ring
    assert box.tail(3)[0]["seq"] == 3
    box.resize(2)
    st = box.stats()
    assert st["capacity"] == 2 and st["events"] == 2
    assert st["dropped"] == 4  # shed on shrink is honest accounting
    assert [e["seq"] for e in box.tail(10)] == [4, 5]
    box.resize(16)
    assert box.stats()["capacity"] == 16
    assert [e["seq"] for e in box.tail(10)] == [4, 5]  # grow keeps all
    with pytest.raises(ValueError):
        box.resize(0)


def test_configure_ring_flag_env_and_validation(monkeypatch):
    original = context.BLACKBOX.maxlen
    try:
        assert context.configure_ring(64) == 64
        assert context.BLACKBOX.maxlen == 64
        monkeypatch.setenv(context.BLACKBOX_EVENTS_ENV, "128")
        assert context.configure_ring() == 128
        monkeypatch.delenv(context.BLACKBOX_EVENTS_ENV)
        # no flag, no env: untouched
        assert context.configure_ring() == 128
        assert context.configure_ring("") == 128
        for bad in ("zero", "0", "-3", "1.5"):
            with pytest.raises(SimulationError) as ei:
                context.configure_ring(bad)
            assert ei.value.code == "E_SPEC"
            assert ei.value.field == "blackbox_events"
    finally:
        context.BLACKBOX.resize(original)


# ---- the runtime device-memory gauge (both branches) ----------------------


class _RichDevice:
    def __str__(self):
        return "FAKE:0"

    def memory_stats(self):
        return {"bytes_in_use": 123.0, "peak_bytes_in_use": 456.0,
                "bytes_limit": 789.0, "irrelevant": 1.0}


class _BlindDevice:
    def __init__(self, name):
        self._name = name

    def __str__(self):
        return self._name

    def memory_stats(self):
        raise RuntimeError("no allocator stats on this backend")


def test_device_memory_stats_allocator_branch(monkeypatch):
    monkeypatch.setattr(jax, "devices", lambda: [_RichDevice()])
    out = tel_runtime._device_memory_stats()
    assert out == {("FAKE:0", "bytes_in_use"): 123.0,
                   ("FAKE:0", "peak_bytes_in_use"): 456.0,
                   ("FAKE:0", "bytes_limit"): 789.0}


def test_device_memory_stats_live_nbytes_fallback(monkeypatch):
    arr = jnp.arange(1024, dtype=jnp.int32)  # 4 KiB, device-resident
    arr.block_until_ready()
    dev = str(next(iter(arr.devices())))
    monkeypatch.setattr(
        jax, "devices", lambda: [_BlindDevice(dev), _BlindDevice("GHOST:9")])
    out = tel_runtime._device_memory_stats()
    # the blind device reports what live arrays hold, labelled distinctly
    assert out[(dev, "live_nbytes")] >= float(arr.nbytes)
    # a blind device holding nothing still renders (explicit zero)
    assert out[("GHOST:9", "live_nbytes")] == 0.0
    assert not any(stat == "bytes_in_use" for _, stat in out)
    del arr


# ---- the launch histogram + in-flight witness ------------------------------


def test_run_launch_observes_histogram_and_inflight():
    fn = "live_test_launch"
    before = live.launch_stats().get(fn, {"count": 0})["count"]
    witnessed = []

    def launch():
        witnessed.append(
            [r for r in live.DEVMEM.inflight_entries() if r["fn"] == fn])
        time.sleep(0.01)
        return "ok"

    assert faults.run_launch(fn, launch) == "ok"
    after = live.launch_stats()[fn]
    assert after["count"] == before + 1
    assert after["sum_s"] > 0 and after["mean_ms"] > 0
    # the launch saw ITS OWN in-flight entry; it is gone afterwards
    assert len(witnessed[0]) == 1
    assert not [r for r in live.DEVMEM.inflight_entries()
                if r["fn"] == fn]


def test_run_launch_failure_not_observed():
    fn = "live_test_launch_fail"

    def boom():
        raise RuntimeError("not a classified fault")

    with pytest.raises(RuntimeError):
        faults.run_launch(fn, boom)
    assert fn not in live.launch_stats()
    assert not [r for r in live.DEVMEM.inflight_entries()
                if r["fn"] == fn]


# ---- multi-worker HTTP: histogram, devmem sections, SSE ~ timeline --------


CLUSTER_YAML = """
apiVersion: v1
kind: Node
metadata: {name: lv0}
status:
  allocatable: {cpu: "8", memory: 16Gi, pods: "110"}
---
apiVersion: apps/v1
kind: Deployment
metadata: {name: app, namespace: default}
spec:
  replicas: 2
  selector: {matchLabels: {app: lv}}
  template:
    metadata: {labels: {app: lv}}
    spec:
      containers:
        - name: c
          resources: {requests: {cpu: "1", memory: 1Gi}}
"""


@pytest.fixture()
def live_server():
    from open_simulator_tpu.server.rest import (
        SimulationServer,
        _make_handler,
    )

    srv = SimulationServer(workers=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(srv))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", srv, \
        httpd.server_address[1]
    srv.begin_drain()  # closes any leftover SSE subscribers
    httpd.shutdown()


def _post(url, payload, trace_id=None):
    headers = {"Content-Type": "application/json"}
    if trace_id:
        headers[context.TRACE_HEADER] = trace_id
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=headers)
    with urllib.request.urlopen(req, timeout=300) as resp:
        return resp.status, json.loads(resp.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=300) as resp:
        return resp.status, json.loads(resp.read())


def test_multiworker_histogram_devmem_and_sse(live_server):
    url, srv, port = live_server
    fn = "serving_lanes"
    base_count = live.launch_stats().get(fn, {"count": 0})["count"]

    # follow the stream BEFORE the load so every event is witnessed live
    frames = []
    ended = threading.Event()

    def follow():
        sock = socket.create_connection(("127.0.0.1", port), timeout=120)
        sock.sendall((f"GET /api/events?follow=1&replay=0 HTTP/1.1\r\n"
                      f"Host: 127.0.0.1:{port}\r\n\r\n").encode())
        buf = b""
        headers_done = False
        try:
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
                if not headers_done:
                    idx = buf.find(b"\r\n\r\n")
                    if idx < 0:
                        continue
                    headers_done = True
                    buf = buf[idx + 4:]
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    data = [ln[6:] for ln in frame.decode().splitlines()
                            if ln.startswith("data: ")]
                    if data:
                        frames.append(json.loads(data[0]))
        except OSError:
            pass
        finally:
            ended.set()
            sock.close()

    reader = threading.Thread(target=follow, daemon=True)
    reader.start()
    deadline = time.time() + 15
    while live.FEED.stats()["subscribers"] < 1:
        assert time.time() < deadline, "subscriber never attached"
        time.sleep(0.02)

    status, out = _post(url + "/api/simulate",
                        {"cluster": {"yaml": CLUSTER_YAML}},
                        trace_id="live-mw-warm")
    assert status == 200
    digest = out["snapshot_digest"]

    # concurrent probes across BOTH workers
    results = []
    lock = threading.Lock()

    def fire(i):
        r = _post(url + "/api/simulate", {"base": digest},
                  trace_id=f"live-mw-{i}")
        with lock:
            results.append(r)

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert all(s == 200 for s, _ in results), results

    # histogram: every completed launch observed exactly once — two
    # workers never clobber each other's counts (coalescing may merge
    # probes into fewer launches, so bound both sides)
    stats = live.launch_stats()[fn]
    grew = stats["count"] - base_count
    assert 1 <= grew <= 7, stats
    code, dbg = _get(url + "/debug/stats")
    assert code == 200
    assert dbg["launches"][fn]["count"] == stats["count"]
    dm = dbg["devmem"]
    assert dm["owners"].get("resident_snapshots", 0) > 0, dm
    assert dm["peak_total"] >= dm["total"], dm
    assert dbg["events_feed"]["subscribers"] >= 1

    # the stream saw one probe's causal kinds; the timeline agrees
    tid = "live-mw-0"
    deadline = time.time() + 15
    while True:
        mine = [f for f in frames if tid in (f.get("traces") or [])]
        kinds = {f["kind"] for f in mine}
        if {"enqueue", "launch", "response"} <= kinds:
            break
        assert time.time() < deadline, (
            "stream never showed the causal sequence", kinds)
        time.sleep(0.05)
    code, tl = _get(url + f"/api/trace/{tid}")
    assert code == 200
    timeline_kinds = {e["kind"] for e in tl["events"]}
    assert {k for k in kinds} <= timeline_kinds, (kinds, timeline_kinds)

    # drain closes the follower; its final frame is the drain record
    srv.begin_drain()
    assert ended.wait(30), "stream did not end on drain"
    assert frames and frames[-1]["kind"] == "drain", frames[-5:]


def test_events_replay_endpoint_without_follow(live_server):
    url, srv, _port = live_server
    status, _ = _post(url + "/api/simulate",
                      {"cluster": {"yaml": CLUSTER_YAML}},
                      trace_id="live-replay-1")
    assert status == 200
    req = urllib.request.Request(url + "/api/events?replay=16")
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = resp.read().decode()
    events = [json.loads(ln[6:]) for ln in body.splitlines()
              if ln.startswith("data: ")]
    assert 0 < len(events) <= 16
    assert any("live-replay-1" in (e.get("traces") or []) for e in events)
    assert all("t_mono" in e for e in events)
