"""Explain decode: golden per-pod report on a small synthetic cluster,
engine top-k contract, Simulator pass-through, and the CLI surface."""

import json
import textwrap

import numpy as np
import pytest

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.telemetry.explain import (
    explain_result,
    first_failing_op,
    format_explain,
)


@pytest.fixture
def small_cluster(node_factory, pod_factory):
    """Two schedulable nodes (one tainted); a pod that fits, a pod whose
    cpu fits nowhere (tolerating the taint, so cpu is the only failure),
    and a pod blocked by the taint on one node and cpu on the other."""
    cluster = ClusterResources()
    cluster.nodes = [
        node_factory("big", cpu_m=4000),
        node_factory("small-tainted", cpu_m=1000, taints=[
            {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]),
    ]
    apps = ClusterResources()
    apps.pods = [
        pod_factory("fits", cpu="500m"),
        pod_factory("too-big", cpu="9000m",
                    tolerations=[{"operator": "Exists"}]),
        pod_factory("squeezed", cpu="3600m"),
    ]
    return cluster, [AppResource("a", apps)]


def test_explain_golden_small_cluster(small_cluster):
    cluster, apps = small_cluster
    result = simulate(cluster, apps, config_overrides={"explain_topk": 2})
    report = explain_result(result)

    assert report["n_active_nodes"] == 2
    assert report["summary"] == {"scheduled": 1, "unscheduled": 2}
    by_pod = {e["pod"]: e for e in report["pods"]}

    fits = by_pod["default/fits"]
    assert fits["status"] == "scheduled" and not fits["forced"]
    # rank-0 candidate IS the chosen node, and its parts sum to its score
    assert fits["candidates"][0]["node"] == fits["node"]
    top = fits["candidates"][0]
    assert sum(top["parts"].values()) == pytest.approx(top["score"], abs=1e-2)
    assert set(top["parts"]) == set(report["score_parts"])

    too_big = by_pod["default/too-big"]
    assert too_big["status"] == "unscheduled"
    assert too_big["first_failing_op"] == "Insufficient cpu"
    assert too_big["eliminations"] == [{"op": "Insufficient cpu", "nodes": 2}]
    assert "0/2 nodes are available" in too_big["reason"]
    assert too_big["candidates"] == []  # neg_inf sentinels dropped

    squeezed = by_pod["default/squeezed"]
    assert squeezed["status"] == "unscheduled"
    # taint fires before the fit rows in the vendored pipeline order
    assert squeezed["first_failing_op"] == (
        "node(s) had taint that the pod didn't tolerate")
    assert {e["op"]: e["nodes"] for e in squeezed["eliminations"]} == {
        "node(s) had taint that the pod didn't tolerate": 1,
        "Insufficient cpu": 1,
    }


def test_explain_matches_engine_fail_counts(small_cluster):
    """The report's per-op decode must be the engine's fail_counts row,
    not a recomputation."""
    cluster, apps = small_cluster
    result = simulate(cluster, apps)
    report = explain_result(result)
    keys = [p.key for p in result.snapshot.pods]
    for entry in report["pods"]:
        if entry["status"] != "unscheduled":
            continue
        i = keys.index(entry["pod"])
        row = np.asarray(result.fail_counts[i])
        assert entry["first_failing_op"] == first_failing_op(row, result.op_names)
        assert sum(e["nodes"] for e in entry["eliminations"]) == int(row.sum())


def test_topk_outputs_off_by_default(small_cluster):
    cluster, apps = small_cluster
    result = simulate(cluster, apps)
    assert result.topk_node is None and result.score_part_names == []
    # explain still works: failure decode only
    report = explain_result(result)
    assert all(e["candidates"] == [] for e in report["pods"])


def test_topk_respects_node_count_and_order(small_cluster):
    cluster, apps = small_cluster
    # ask for more candidates than nodes: K clamps to N
    result = simulate(cluster, apps, config_overrides={"explain_topk": 16})
    assert result.topk_node.shape == (3, 2)
    report = explain_result(result, top_k=1)
    fits = next(e for e in report["pods"] if e["pod"] == "default/fits")
    assert len(fits["candidates"]) == 1
    # candidate scores are non-increasing in rank
    full = explain_result(result)
    for e in full["pods"]:
        scores = [c["score"] for c in e["candidates"]]
        assert scores == sorted(scores, reverse=True)


def test_explain_negative_topk_clamped(small_cluster):
    cluster, apps = small_cluster
    result = simulate(cluster, apps, config_overrides={"explain_topk": 2})
    report = explain_result(result, top_k=-1)
    assert all(e["candidates"] == [] for e in report["pods"])


def test_explain_pod_filter_and_format(small_cluster):
    cluster, apps = small_cluster
    result = simulate(cluster, apps, config_overrides={"explain_topk": 2})
    report = explain_result(result, pods=["default/too-big"])
    assert [e["pod"] for e in report["pods"]] == ["default/too-big"]
    text = format_explain(explain_result(result))
    assert "default/fits: scheduled on" in text
    assert "default/too-big: UNSCHEDULABLE" in text
    assert "first failing op: Insufficient cpu" in text
    assert "candidate" in text


def test_forced_pod_marked(node_factory, pod_factory):
    cluster = ClusterResources()
    cluster.nodes = [node_factory("n0"), node_factory("n1")]
    cluster.pods = [pod_factory("pinned", node_name="n1")]
    result = simulate(cluster, [], config_overrides={"explain_topk": 2})
    report = explain_result(result)
    [entry] = report["pods"]
    assert entry["forced"] and entry["status"] == "scheduled"
    assert entry["node"] == "n1"
    assert "pinned via spec.nodeName" in format_explain(report)


def test_preempted_status_from_structured_marker(node_factory, pod_factory):
    """Preempted victims are flagged via SimulateResult.preempted_pod_keys,
    not by matching the reason string's wording."""
    cluster = ClusterResources()
    cluster.nodes = [node_factory("solo", cpu_m=1000)]
    cluster.pods = [pod_factory("low", cpu="800m")]
    high = pod_factory("high", cpu="800m")
    high.priority = 1000
    apps = ClusterResources()
    apps.pods = [high]
    result = simulate(cluster, [AppResource("a", apps)])
    assert result.preempted_pod_keys == ["default/low"]
    report = explain_result(result)
    entry = next(e for e in report["pods"] if e["pod"] == "default/low")
    assert entry["status"] == "preempted"
    assert "preempted" in entry["reason"]
    placed = next(e for e in report["pods"] if e["pod"] == "default/high")
    assert placed["status"] == "scheduled" and placed["node"] == "solo"


def test_simulator_session_carries_explain_surface(node_factory, pod_factory):
    from open_simulator_tpu.simulator import Simulator

    cluster = ClusterResources()
    cluster.nodes = [node_factory("n0", cpu_m=2000)]
    sim = Simulator(cluster, config_overrides={"explain_topk": 2})
    sim.run_cluster()
    apps = ClusterResources()
    apps.pods = [pod_factory("w", cpu="500m",
                             labels={"simon/app-name": "webapp"})]
    res = sim.schedule_app(AppResource("webapp", apps))
    # the trimmed per-app result still decodes (rows index the snapshot)
    report = explain_result(res)
    entry = next(e for e in report["pods"] if e["pod"] == "default/w")
    assert entry["status"] == "scheduled" and entry["candidates"]
    # trimmed result: explain covers ONLY the result's own pods — pods
    # outside the app must not be mislabeled unscheduled from absence
    assert {e["pod"] for e in report["pods"]} == {"default/w"}
    assert all(e["status"] != "unscheduled" or e.get("reason")
               for e in report["pods"])


def test_explain_cli_json_and_trace_out(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    cluster_dir = tmp_path / "cluster"
    cluster_dir.mkdir()
    (cluster_dir / "node.yaml").write_text(textwrap.dedent("""
        apiVersion: v1
        kind: Node
        metadata: {name: c0}
        status:
          allocatable: {cpu: '2', memory: 4Gi, pods: '110'}
    """))
    app_dir = tmp_path / "app"
    app_dir.mkdir()
    (app_dir / "pods.yaml").write_text(textwrap.dedent("""
        apiVersion: v1
        kind: Pod
        metadata: {name: ok, namespace: default}
        spec:
          containers: [{name: c, resources: {requests: {cpu: 500m}}}]
        ---
        apiVersion: v1
        kind: Pod
        metadata: {name: nope, namespace: default}
        spec:
          containers: [{name: c, resources: {requests: {cpu: '32'}}}]
    """))
    config = tmp_path / "config.yaml"
    config.write_text(textwrap.dedent("""
        apiVersion: simon/v1alpha1
        kind: Config
        metadata: {name: explain-test}
        spec:
          cluster: {customConfig: cluster}
          appList:
            - {name: app, path: app}
    """))
    trace_path = tmp_path / "trace.json"
    rc = main(["explain", "-f", str(config), "--json",
               "--trace-out", str(trace_path)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["summary"] == {"scheduled": 1, "unscheduled": 1}
    nope = next(e for e in report["pods"] if e["pod"] == "default/nope")
    assert nope["first_failing_op"] == "Insufficient cpu"

    # --trace-out wrote a Perfetto-loadable Chrome trace with the nested
    # simulate phases
    doc = json.loads(trace_path.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"simulate", "encode", "schedule", "decode"} <= names
    sim_ev = next(e for e in doc["traceEvents"] if e["name"] == "simulate")
    enc_ev = next(e for e in doc["traceEvents"] if e["name"] == "encode")
    assert sim_ev["ts"] <= enc_ev["ts"]
    assert enc_ev["ts"] + enc_ev["dur"] <= sim_ev["ts"] + sim_ev["dur"] + 1
    # a cache-miss "compile" event must nest strictly INSIDE schedule —
    # Perfetto nests by containment, an overlapping sibling renders wrong
    if "compile" in names:
        sch = next(e for e in doc["traceEvents"] if e["name"] == "schedule")
        comp = next(e for e in doc["traceEvents"] if e["name"] == "compile")
        assert sch["ts"] <= comp["ts"]
        assert comp["ts"] + comp["dur"] <= sch["ts"] + sch["dur"]


def test_explain_cli_missing_config_errors(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    rc = main(["explain", "-f", str(tmp_path / "nope.yaml")])
    assert rc == 1
    assert "error:" in capsys.readouterr().err


def test_chaos_cli_unwritable_trace_out_errors_cleanly(capsys):
    """An unwritable --trace-out path must exit 1 with an error message
    like apply/explain, not escape as a traceback."""
    from open_simulator_tpu.cli.main import main

    rc = main(["chaos", "--cluster-config", "examples/cluster/demo",
               "--kill-node", "worker-a-0",
               "--trace-out", "/nonexistent-dir/t.json"])
    assert rc == 1
    assert "error:" in capsys.readouterr().err
