"""open-local storage columns, MaxVG, scheduler-config weight overrides,
random tie-break."""

import json
import textwrap

import numpy as np

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.engine.sched_config import weight_overrides_from_file
from open_simulator_tpu.k8s.loader import ClusterResources, make_valid_node
from open_simulator_tpu.k8s.local_storage import RES_DEVICE_HDD, RES_VG
from open_simulator_tpu.k8s.objects import ANNO_NODE_LOCAL_STORAGE, ANNO_POD_LOCAL_STORAGE
from tests.conftest import make_node, make_pod

GIB = 1024 ** 3


def storage_node(name, vg_gib=100, hdd=1):
    n = make_node(name)
    n.meta.annotations[ANNO_NODE_LOCAL_STORAGE] = json.dumps({
        "vgs": [{"name": "pool", "capacity": str(vg_gib * GIB)}],
        "devices": [{"name": f"/dev/sd{i}", "capacity": str(100 * GIB),
                     "mediaType": "hdd", "isAllocated": "false"} for i in range(hdd)],
    })
    return n


def lvm_pod(name, size_gib):
    p = make_pod(name, cpu="100m", mem="128Mi")
    p.meta.annotations[ANNO_POD_LOCAL_STORAGE] = json.dumps({
        "volumes": [{"size": str(size_gib * GIB), "kind": "LVM", "scName": "open-local-lvm"}]
    })
    return p


def test_node_storage_columns():
    n = make_valid_node(storage_node("s0", vg_gib=100, hdd=2))
    assert n.allocatable[RES_VG] == 100 * 1024
    assert n.allocatable[RES_DEVICE_HDD] == 2


def test_vg_fit_enforced():
    cluster = ClusterResources()
    cluster.nodes = [storage_node("s0", vg_gib=100)]
    app = ClusterResources()
    app.pods = [lvm_pod("v0", 60), lvm_pod("v1", 60)]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(res.scheduled_pods) == 1
    assert len(res.unscheduled_pods) == 1
    assert f"Insufficient {RES_VG}" in res.unscheduled_pods[0].reason


def test_device_volume_counts():
    cluster = ClusterResources()
    cluster.nodes = [storage_node("s0", hdd=1)]
    app = ClusterResources()
    p = make_pod("d0", cpu="100m")
    p.meta.annotations[ANNO_POD_LOCAL_STORAGE] = json.dumps({
        "volumes": [{"size": str(10 * GIB), "kind": "HDD", "scName": "open-local-device-hdd"}]
    })
    p2 = make_pod("d1", cpu="100m")
    p2.meta.annotations[ANNO_POD_LOCAL_STORAGE] = p.meta.annotations[ANNO_POD_LOCAL_STORAGE]
    app.pods = [p, p2]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(res.scheduled_pods) == 1  # only one exclusive HDD device


def test_weight_overrides(tmp_path):
    cfg = tmp_path / "sched.yaml"
    cfg.write_text(textwrap.dedent("""
        apiVersion: kubescheduler.config.k8s.io/v1beta2
        kind: KubeSchedulerConfiguration
        profiles:
          - plugins:
              score:
                enabled:
                  - name: NodeResourcesFit
                    weight: 5
                  - name: Simon
                    weight: 3
                disabled:
                  - name: PodTopologySpread
    """))
    ov = weight_overrides_from_file(str(cfg))
    assert ov == {"w_least": 5.0, "w_simon": 3.0, "w_spread": 0.0}


def test_tie_break_seed_changes_only_ties():
    from open_simulator_tpu.core import build_pod_sequence
    from open_simulator_tpu.encode.snapshot import encode_cluster
    from open_simulator_tpu.engine.scheduler import device_arrays, make_config, schedule_pods

    cluster = ClusterResources()
    cluster.nodes = [make_node(f"n{i}") for i in range(4)]  # identical nodes -> ties
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}") for i in range(8)]
    pods = build_pod_sequence(cluster, [AppResource(name="a", resources=app)])
    snap = encode_cluster(cluster.nodes, pods)
    arrs = device_arrays(snap)

    a = np.asarray(schedule_pods(arrs, arrs.active, make_config(snap, tie_break_seed=7)).node)
    b = np.asarray(schedule_pods(arrs, arrs.active, make_config(snap, tie_break_seed=8)).node)
    det = np.asarray(schedule_pods(arrs, arrs.active, make_config(snap)).node)
    # all variants schedule everything...
    assert (a >= 0).all() and (b >= 0).all() and (det >= 0).all()
    # ...and different seeds produce different tie resolution on identical nodes
    assert not np.array_equal(a, b) or not np.array_equal(a, det)


def test_plugin_config_scoring_strategy(tmp_path):
    # NodeResourcesFitArgs.scoringStrategy: MostAllocated moves the fit
    # weight onto the bin-packing score (the v1beta2+ replacement for the
    # NodeResourcesMostAllocated plugin).
    cfg = tmp_path / "sched.yaml"
    cfg.write_text(textwrap.dedent("""
        apiVersion: kubescheduler.config.k8s.io/v1beta2
        kind: KubeSchedulerConfiguration
        profiles:
          - plugins:
              score:
                enabled:
                  - name: NodeResourcesFit
                    weight: 4
            pluginConfig:
              - name: NodeResourcesFit
                args:
                  scoringStrategy:
                    type: MostAllocated
    """))
    ov = weight_overrides_from_file(str(cfg))
    assert ov == {"w_least": 0.0, "w_most": 4.0}


def test_plugin_config_least_allocated_noop(tmp_path):
    cfg = tmp_path / "sched.yaml"
    cfg.write_text(textwrap.dedent("""
        kind: KubeSchedulerConfiguration
        profiles:
          - pluginConfig:
              - name: NodeResourcesFit
                args:
                  scoringStrategy:
                    type: LeastAllocated
    """))
    ov = weight_overrides_from_file(str(cfg))
    assert ov == {"w_least": 1.0}
