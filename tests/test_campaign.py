"""Fleet campaigns (ISSUE 8): per-cluster fault isolation + quarantine,
campaign checkpoint/resume (SIGKILL subprocess acceptance), the placement
invariant auditor, fleet analytics, degrade-to-disabled ledger and
checkpoint dirs, and the fuzzed admission boundary."""

import json
import logging
import os
import random
import signal
import subprocess
import sys

import numpy as np
import pytest

from open_simulator_tpu.campaign import (
    AuditError,
    CampaignOptions,
    audit_result,
    discover_fleet,
    format_audit,
    format_report,
    load_and_admit,
    report_from_journal,
    resolve_campaign,
    run_campaign,
    write_synthetic_fleet,
)
from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.k8s.cluster_source import ClusterSourceError
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.resilience import lifecycle
from open_simulator_tpu.resilience.journal import unframe_line
from tests.conftest import make_node, make_pod

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "api_dump.json")


@pytest.fixture
def fleet_dir(tmp_path):
    d = tmp_path / "fleet"
    write_synthetic_fleet(str(d), n_clusters=3, nodes=4, pods=12,
                          malformed=1)
    return str(d)


@pytest.fixture
def no_checkpoint(monkeypatch):
    monkeypatch.delenv(lifecycle.CHECKPOINT_DIR_ENV, raising=False)
    monkeypatch.delenv("SIMON_LEDGER_DIR", raising=False)
    from open_simulator_tpu.telemetry import ledger

    ledger.configure(None)


# ---- fault isolation -----------------------------------------------------


def test_poisoned_cluster_quarantined_campaign_continues(fleet_dir,
                                                         no_checkpoint):
    report = run_campaign(CampaignOptions(fleet=fleet_dir,
                                          checkpoint=False))
    t = report["totals"]
    assert t == {"clusters": 3, "completed": 2, "quarantined": 1,
                 "placed": t["placed"], "unplaced": t["unplaced"]}
    assert t["placed"] > 0
    [quar] = report["quarantined"]
    assert quar["cluster"] == "cluster-02"
    assert quar["error"]["code"] == "E_SOURCE"
    assert "line" in quar["error"]["field"]
    assert quar["attempts"] == 1  # E_SOURCE is deterministic: no retries
    # every OTHER cluster completed, audit-clean
    assert [r["cluster"] for r in report["clusters"]] == [
        "cluster-00", "cluster-01"]
    assert all(r["audit_ok"] for r in report["clusters"])
    assert report["quarantine_summary"] == {"E_SOURCE": 1}
    # heterogeneous fleet, shared executables: two shape buckets
    assert len(report["buckets"]) == 2
    # the renderer holds together
    text = format_report(report)
    assert "QUARANTINED [E_SOURCE]" in text and "cluster-00" in text


def test_audit_violation_quarantines_with_e_audit(fleet_dir, no_checkpoint,
                                                  monkeypatch):
    """A corrupted result (engine-bug stand-in) must quarantine THAT
    cluster with E_AUDIT while the rest of the fleet completes."""
    real_simulate = simulate

    def corrupting(cluster, apps, **kw):
        result = real_simulate(cluster, apps, **kw)
        if result.scheduled_pods and \
                cluster.nodes[0].name.startswith("cluster-00"):
            # bind a pod to a node that does not exist in the snapshot
            result.scheduled_pods[0].node_name = "ghost-node"
        return result

    monkeypatch.setattr("open_simulator_tpu.core.simulate", corrupting)
    report = run_campaign(CampaignOptions(fleet=fleet_dir,
                                          checkpoint=False))
    codes = {q["cluster"]: q["error"]["code"]
             for q in report["quarantined"]}
    assert codes == {"cluster-00": "E_AUDIT", "cluster-02": "E_SOURCE"}
    assert [r["cluster"] for r in report["clusters"]] == ["cluster-01"]
    audit_err = next(q for q in report["quarantined"]
                     if q["cluster"] == "cluster-00")["error"]
    assert "audit" in audit_err and not audit_err["audit"]["ok"]


def test_transient_failures_retry_with_history(fleet_dir, no_checkpoint,
                                               monkeypatch):
    """Only classifier-transient failures (resilience/faults.py) spend
    the retry budget; persistent transients quarantine with the attempt
    count, and a deterministic-classed fault quarantines on attempt 1
    instead of being retried like a transient."""
    calls = {"n": 0}
    real_simulate = simulate

    def flaky(cluster, apps, **kw):
        if cluster.nodes[0].name.startswith("cluster-00"):
            calls["n"] += 1
            if calls["n"] == 1:
                # E_TRANSFER-classed: the retry-worthy class
                raise OSError("DATA_LOSS: failed to transfer buffer")
        return real_simulate(cluster, apps, **kw)

    monkeypatch.setattr("open_simulator_tpu.core.simulate", flaky)
    report = run_campaign(CampaignOptions(fleet=fleet_dir,
                                          checkpoint=False,
                                          backoff_s=0.0))
    assert report["totals"]["completed"] == 2  # the flake recovered
    assert calls["n"] == 2

    def always_down(cluster, apps, **kw):
        if cluster.nodes[0].name.startswith("cluster-00"):
            raise OSError("connection reset by peer")
        return real_simulate(cluster, apps, **kw)

    monkeypatch.setattr("open_simulator_tpu.core.simulate", always_down)
    report = run_campaign(CampaignOptions(fleet=fleet_dir,
                                          checkpoint=False, retries=2,
                                          backoff_s=0.0))
    quar = next(q for q in report["quarantined"]
                if q["cluster"] == "cluster-00")
    assert quar["error"]["code"] == "E_INTERNAL"
    assert quar["attempts"] == 3 and quar["transient_retries"] == 2

    # the satellite's point: a deterministic fault (an OOM) must NOT
    # burn the retry budget reproducing itself — one attempt, quarantined
    det_calls = {"n": 0}

    def oom(cluster, apps, **kw):
        if cluster.nodes[0].name.startswith("cluster-00"):
            det_calls["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return real_simulate(cluster, apps, **kw)

    monkeypatch.setattr("open_simulator_tpu.core.simulate", oom)
    report = run_campaign(CampaignOptions(fleet=fleet_dir,
                                          checkpoint=False, retries=2,
                                          backoff_s=0.0))
    quar = next(q for q in report["quarantined"]
                if q["cluster"] == "cluster-00")
    assert det_calls["n"] == 1
    assert quar["attempts"] == 1 and quar["transient_retries"] == 0


def test_cancellation_observed_at_cluster_boundary(fleet_dir,
                                                   no_checkpoint):
    token = lifecycle.CancelToken()
    token.cancel("drain")
    with lifecycle.cancel_scope(token):
        with pytest.raises(lifecycle.CancelledError) as ei:
            run_campaign(CampaignOptions(fleet=fleet_dir,
                                         checkpoint=False))
    assert "campaign cluster boundary" in str(ei.value)
    assert "clusters_settled" in ei.value.partial


# ---- checkpoint / resume -------------------------------------------------


def _campaign_child():
    """Subprocess entry: SIGKILL self after the first settled cluster's
    journal line lands (test_sigkill_mid_campaign...)."""
    from open_simulator_tpu.campaign import runner as campaign_runner

    real_append = campaign_runner.CampaignJournal._append

    def kamikaze(self, rec):
        real_append(self, rec)
        if rec.get("kind") in ("cluster", "quarantine"):
            os.kill(os.getpid(), signal.SIGKILL)

    campaign_runner.CampaignJournal._append = kamikaze
    # serial boundary on purpose: this test pins the SERIAL settlement
    # order (first journal line = first cluster's row); the fleet-lane
    # path settles prepass quarantines before batched rows and has its
    # own journal/report coverage in test_tune.py. The PARENT resume
    # below runs the default (lane) mode, so serial-journal -> lane-mode
    # resume compatibility is exactly what this test now also proves.
    run_campaign(CampaignOptions(fleet=os.environ["TEST_FLEET"],
                                 fleet_lanes=False))
    raise SystemExit("unreachable")


def test_sigkill_mid_campaign_then_resume_bit_identical(fleet_dir,
                                                        tmp_path,
                                                        no_checkpoint):
    """ISSUE 8 acceptance: SIGKILL mid-campaign, parent resumes via
    --resume, fleet report digest bit-identical, quarantined clusters
    reported once (not re-run, not lost)."""
    reference = run_campaign(CampaignOptions(fleet=fleet_dir,
                                             checkpoint=False))

    ckpt = tmp_path / "ckpt"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TEST_FLEET": fleet_dir,
           lifecycle.CHECKPOINT_DIR_ENV: str(ckpt)}
    proc = subprocess.run(
        [sys.executable, "-c",
         "from tests.test_campaign import _campaign_child; "
         "_campaign_child()"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == -signal.SIGKILL, (
        f"child should die by SIGKILL, got rc={proc.returncode}\n"
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}")

    [name] = [n for n in os.listdir(ckpt) if n.endswith(".campaign.jsonl")]
    with open(ckpt / name, encoding="utf-8") as f:
        kinds = [json.loads(unframe_line(ln))["kind"] for ln in f
                 if ln.strip()]
    assert kinds == ["header", "cluster"]  # torn mid-campaign

    os.environ[lifecycle.CHECKPOINT_DIR_ENV] = str(ckpt)
    try:
        resumed = run_campaign(CampaignOptions(fleet=fleet_dir,
                                               resume="last"))
        # the journal is the report's source of truth either way
        journal = resolve_campaign("last")
    finally:
        del os.environ[lifecycle.CHECKPOINT_DIR_ENV]
    assert resumed["resumed_clusters"] == 1
    assert resumed["digest"] == reference["digest"]
    assert resumed["totals"] == reference["totals"]
    # quarantined exactly once: in the report AND in the journal
    assert [q["cluster"] for q in resumed["quarantined"]] == ["cluster-02"]
    assert journal.done is not None
    assert journal.done["digest"] == reference["digest"]
    assert report_from_journal(journal)["digest"] == reference["digest"]
    quar_lines = [r for r in journal.records if r["kind"] == "quarantine"]
    assert len(quar_lines) == 1


def test_resume_fleet_drift_is_structured(fleet_dir, tmp_path,
                                          no_checkpoint, monkeypatch):
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path / "ck"))
    run_campaign(CampaignOptions(fleet=fleet_dir))
    # mutate one dump: the fleet digest drifts, resume must refuse
    target = os.path.join(fleet_dir, "cluster-00.json")
    with open(target, "a", encoding="utf-8") as f:
        f.write("\n")
    with pytest.raises(lifecycle.ResumeError, match="fleet drifted"):
        run_campaign(CampaignOptions(fleet=fleet_dir, resume="last"))


def test_resume_unknown_id_and_no_dir(fleet_dir, no_checkpoint, tmp_path,
                                      monkeypatch):
    with pytest.raises(lifecycle.ResumeError, match="no checkpoint"):
        run_campaign(CampaignOptions(fleet=fleet_dir, resume="last"))
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    with pytest.raises(lifecycle.ResumeError, match="no campaign"):
        run_campaign(CampaignOptions(fleet=fleet_dir, resume="last"))


# ---- the auditor ---------------------------------------------------------


def _small_result():
    cluster = ClusterResources()
    cluster.nodes = [make_node("n0", cpu_m=4000, mem_mib=8192),
                     make_node("n1", cpu_m=4000, mem_mib=8192)]
    cluster.pods = [make_pod("bound", cpu="500m", node_name="n0")]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu="500m", mem="256Mi")
                for i in range(4)]
    return simulate(cluster, [AppResource(name="a", resources=app)])


def test_audit_clean_result_passes(no_checkpoint):
    result = _small_result()
    rep = audit_result(result)
    assert rep.ok and rep.n_violations == 0
    assert rep.n_bound == 5 and rep.n_active_nodes == 2
    assert rep.cpu_pct > 0
    assert "binding" in rep.checks and "forced" in rep.checks
    assert "PASS" in format_audit(rep, name="small")


def _free_scheduled(result):
    """A scheduled pod WITHOUT a forced bind (doctoring a forced pod
    would trip the forced_bind check too — also correct, but these
    tests want one violation kind at a time)."""
    import numpy as np

    forced = np.asarray(result.snapshot.arrays.forced_node)
    for sp in result.scheduled_pods:
        pi = next(i for i, p in enumerate(result.snapshot.pods)
                  if p is sp.pod)
        if forced[pi] < 0:
            return sp
    raise AssertionError("no free scheduled pod in the fixture")


def test_audit_flags_unknown_and_inactive_node(no_checkpoint):
    result = _small_result()
    _free_scheduled(result).node_name = "ghost"
    rep = audit_result(result)
    assert not rep.ok
    assert {v.kind for v in rep.violations} == {"unknown_node"}

    result = _small_result()
    # drop n1 from node_status: pods bound there become inactive-node binds
    dropped = [ns for ns in result.node_status if ns.node.name != "n1"]
    had_on_n1 = any(sp.node_name == "n1" for sp in result.scheduled_pods)
    result.node_status = dropped
    rep = audit_result(result)
    if had_on_n1:
        assert {v.kind for v in rep.violations} == {"inactive_node"}
    else:
        assert rep.ok


def test_audit_flags_overcommit_and_forced_drift(no_checkpoint):
    result = _small_result()
    arrs = result.snapshot.arrays
    # inflate every request 100x post-hoc: consumption > allocatable
    result.snapshot.arrays = arrs.replace(req=np.asarray(arrs.req) * 100.0)
    rep = audit_result(result)
    assert not rep.ok
    assert "overcommit" in {v.kind for v in rep.violations}

    result = _small_result()
    arrs = result.snapshot.arrays
    forced = np.asarray(arrs.forced_node).copy()
    # claim pod 0 was pinned to the OTHER node than it landed on
    placed_on = result.scheduled_pods[0].node_name
    other = 1 if placed_on == result.snapshot.node_names[0] else 0
    pi = result.snapshot.pods.index(result.scheduled_pods[0].pod)
    forced[pi] = other
    result.snapshot.arrays = arrs.replace(forced_node=forced)
    rep = audit_result(result)
    assert any(v.kind == "forced_bind" for v in rep.violations)


def test_audit_error_payload_is_structured(no_checkpoint):
    result = _small_result()
    _free_scheduled(result).node_name = "ghost"
    rep = audit_result(result)
    err = AuditError(rep, ref="cluster/x")
    assert err.code == "E_AUDIT"
    d = err.to_dict()
    assert d["audit"]["n_violations"] == 1
    assert d["audit"]["violations"][0]["kind"] == "unknown_node"


def test_audit_cli_standalone(no_checkpoint, capsys):
    from open_simulator_tpu.cli.main import main

    rc = main(["campaign", "audit", FIXTURE])
    assert rc == 0
    assert "PASS" in capsys.readouterr().out


# ---- fuzzed admission boundary (satellite) -------------------------------


def _mutate(doc, rng):
    """One seeded random mutation: dropped keys, wrong types, negative
    quantities, bogus kinds — the ISSUE 8 fuzz families."""
    doc = json.loads(json.dumps(doc))  # deep copy
    items = doc.get("items", [])
    kind = rng.randrange(6)
    if kind == 0 and items:       # drop a random key somewhere
        obj = rng.choice(items)
        if obj:
            obj.pop(rng.choice(sorted(obj)), None)
    elif kind == 1 and items:     # wrong type for a random field
        obj = rng.choice(items)
        key = rng.choice(sorted(obj)) if obj else None
        if key:
            obj[key] = rng.choice([42, ["x"], "zzz", None])
    elif kind == 2:               # negative / malformed quantity
        for obj in items:
            if obj.get("kind") == "Pod":
                c = obj.setdefault("spec", {}).setdefault(
                    "containers", [{}])[0]
                c.setdefault("resources", {})["requests"] = {
                    "cpu": rng.choice(["-1", "2x", "--", "1e999m"]),
                    "memory": "-5Gi"}
                break
    elif kind == 3 and items:     # bogus kind
        rng.choice(items)["kind"] = rng.choice(
            ["Frobnicator", 7, "", None])
    elif kind == 4 and items:     # metadata mangled to a scalar
        rng.choice(items)["metadata"] = rng.choice([3, "meta", ["x"]])
    else:                         # nested status/spec mangled
        if items:
            obj = rng.choice(items)
            obj[rng.choice(["status", "spec"])] = rng.choice(
                [17, "nope", [1, 2]])
    return doc


def test_fuzzed_dumps_yield_structured_errors_only(tmp_path,
                                                   no_checkpoint):
    """~50 seeded mutations of a valid dump: the campaign admission
    boundary must answer each with success or a structured
    SimulationError — never an uncaught traceback."""
    with open(FIXTURE, encoding="utf-8") as f:
        base = json.load(f)
    rng = random.Random(1208)
    outcomes = {"ok": 0, "structured": 0}
    for i in range(50):
        doc = _mutate(base, rng)
        path = tmp_path / f"mutant-{i:02d}.json"
        text = json.dumps(doc)
        if i % 10 == 9:  # every 10th: truncate mid-stream instead
            text = text[:rng.randrange(1, max(2, len(text) - 1))]
        path.write_text(text)
        try:
            load_and_admit(str(path))
            outcomes["ok"] += 1
        except SimulationError as e:
            assert e.code, f"mutant {i}: structured error without a code"
            assert isinstance(e.to_dict(), dict)
            outcomes["structured"] += 1
        # anything else propagates and fails the test — by design
    assert outcomes["structured"] > 0, outcomes
    assert sum(outcomes.values()) == 50


# ---- degrade-to-disabled dirs (satellite) --------------------------------


def test_unwritable_ledger_degrades_with_one_warning(tmp_path, caplog,
                                                     no_checkpoint):
    """A readonly/unwritable ledger dir must cost exactly ONE warning and
    disable recording — never crash a campaign. (Under root a chmod-0
    dir is still writable, so the unwritable parent is a regular file —
    the same OSError class a full disk raises.)"""
    from open_simulator_tpu.telemetry import ledger

    blocker = tmp_path / "blocker"
    blocker.write_text("not a dir")
    bad_dir = str(blocker / "ledger")
    ledger.configure(bad_dir)
    try:
        assert ledger.enabled()
        with caplog.at_level(logging.WARNING,
                             logger="open_simulator_tpu.telemetry.ledger"):
            with ledger.run_capture("simulate") as cap:
                assert cap.recording
            assert not ledger.enabled()          # latched off
            with ledger.run_capture("simulate") as cap2:
                assert not cap2.recording        # second run: free no-op
            assert ledger.append_event("x") is None
        warnings = [r for r in caplog.records if "unwritable" in r.message]
        assert len(warnings) == 1, [r.message for r in caplog.records]
        # reconfiguring clears the latch
        good = tmp_path / "ledger-ok"
        ledger.configure(str(good))
        assert ledger.enabled()
        with ledger.run_capture("simulate") as cap3:
            assert cap3.recording
    finally:
        ledger.configure(None)


def test_unwritable_checkpoint_dir_campaign_still_runs(fleet_dir, tmp_path,
                                                       no_checkpoint,
                                                       monkeypatch,
                                                       caplog):
    blocker = tmp_path / "blocker"
    blocker.write_text("not a dir")
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV,
                       str(blocker / "ckpt"))
    with caplog.at_level(logging.WARNING):
        report = run_campaign(CampaignOptions(fleet=fleet_dir))
    assert report["totals"]["completed"] == 2
    assert any("checkpointing disabled" in r.message
               for r in caplog.records)


def test_sweep_journal_append_degrades_once(tmp_path, caplog):
    journal = lifecycle.SweepJournal.create(
        str(tmp_path), {"engine": "x"}, 4, 2, (100.0, 100.0, 100.0))
    blocker = tmp_path / "f"
    blocker.write_text("file")
    journal.path = str(blocker / "nope.sweep.jsonl")  # now unwritable
    with caplog.at_level(logging.WARNING):
        journal.append_round([1], {1: {"nodes": [0]}})
        journal.append_round([2], {2: {"nodes": [0]}})
        journal.finish(1, "d")
    assert journal.broken
    warnings = [r for r in caplog.records if "unwritable" in r.message]
    assert len(warnings) == 1


def test_sweep_checkpoint_create_degrades(tmp_path, monkeypatch,
                                          no_checkpoint, caplog):
    """An unwritable checkpoint dir must not kill a capacity bisection."""
    from open_simulator_tpu.encode.snapshot import EncodeOptions, encode_cluster
    from open_simulator_tpu.engine.scheduler import make_config
    from open_simulator_tpu.k8s.loader import make_valid_node
    from open_simulator_tpu.parallel.sweep import capacity_bisect

    cluster = ClusterResources()
    cluster.nodes = [make_node("r0", cpu_m=2000, mem_mib=4096)]
    pods = [make_pod(f"p{i}", cpu="1500m") for i in range(3)]
    template = make_node("t", cpu_m=4000, mem_mib=8192)
    snap = encode_cluster(
        [make_valid_node(n) for n in cluster.nodes], pods,
        EncodeOptions(max_new_nodes=2, new_node_template=template))
    blocker = tmp_path / "f"
    blocker.write_text("file")
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(blocker / "ck"))
    with caplog.at_level(logging.WARNING):
        plan = capacity_bisect(snap, make_config(snap), 2, lanes=2)
    assert plan.best_count is not None
    assert plan.sweep_id is None  # checkpointing degraded to off
    assert any("checkpointing disabled" in r.message
               for r in caplog.records)


# ---- surfaces ------------------------------------------------------------


def test_campaign_cli_run_and_report(fleet_dir, tmp_path, no_checkpoint,
                                     monkeypatch, capsys):
    from open_simulator_tpu.cli.main import main

    ledger_dir = tmp_path / "ledger"
    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path / "ck"))
    rc = main(["campaign", "run", "--fleet", fleet_dir,
               "--ledger-dir", str(ledger_dir), "--json"])
    out = capsys.readouterr().out
    assert rc == 0  # a quarantined cluster must NOT fail the fleet
    report = json.loads(out)
    assert report["totals"] == {"clusters": 3, "completed": 2,
                                "quarantined": 1,
                                "placed": report["totals"]["placed"],
                                "unplaced": report["totals"]["unplaced"]}

    # per-(cluster, scenario) RunRecords tagged with the campaign id:
    # one per completed cluster plus the campaign summary event
    rc = main(["runs", "--ledger-dir", str(ledger_dir), "list",
               "--campaign", report["campaign_id"], "--json"])
    assert rc == 0
    runs = json.loads(capsys.readouterr().out)
    assert len(runs) == 3
    assert sum(1 for r in runs if r["digest"]) == 2  # the cluster records

    rc = main(["campaign", "report", report["campaign_id"][:6], "--json"])
    assert rc == 0
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["digest"] == report["digest"]

    from open_simulator_tpu.telemetry import ledger as ledger_mod

    ledger_mod.configure(None)


def test_campaign_rest_route(fleet_dir, no_checkpoint):
    from open_simulator_tpu.server.rest import SimulationServer

    srv = SimulationServer()
    report = srv.campaign({"fleet": fleet_dir, "audit": True})
    assert report["totals"]["completed"] == 2
    assert report["totals"]["quarantined"] == 1

    with pytest.raises(SimulationError) as ei:
        srv.campaign({})
    assert ei.value.code == "E_BAD_REQUEST"

    paths = [os.path.join(fleet_dir, "cluster-00.json")]
    report = srv.campaign({"clusters": paths})
    assert report["totals"] == {"clusters": 1, "completed": 1,
                                "quarantined": 0,
                                "placed": report["totals"]["placed"],
                                "unplaced": 0}


def test_fleet_manifest_and_errors(tmp_path, fleet_dir):
    manifest = tmp_path / "fleet.yaml"
    manifest.write_text(
        "clusters:\n"
        f"  - {os.path.join(fleet_dir, 'cluster-00.json')}\n"
        f"  - name: second\n"
        f"    path: {os.path.join(fleet_dir, 'cluster-01.json')}\n")
    entries = discover_fleet(str(manifest))
    assert [e.name for e in entries] == ["cluster-00", "second"]
    assert all(e.digest for e in entries)

    with pytest.raises(ClusterSourceError, match="does not exist"):
        discover_fleet(str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ClusterSourceError, match="no cluster dumps"):
        discover_fleet(str(empty))
    bad = tmp_path / "bad.yaml"
    bad.write_text("just a string")
    with pytest.raises(ClusterSourceError, match="manifest"):
        discover_fleet(str(bad))


def test_vanished_dump_quarantines_not_aborts(fleet_dir, tmp_path,
                                              no_checkpoint):
    """A dump that is missing/unreadable at DISCOVERY time (deleted
    between listdir and open, stale mount) must quarantine that cluster,
    not abort the campaign — fault isolation is per cluster."""
    manifest = tmp_path / "fleet.yaml"
    manifest.write_text(
        "clusters:\n"
        f"  - {os.path.join(fleet_dir, 'cluster-00.json')}\n"
        f"  - {os.path.join(fleet_dir, 'vanished.json')}\n")
    report = run_campaign(CampaignOptions(fleet=str(manifest),
                                          checkpoint=False))
    assert report["totals"]["completed"] == 1
    [quar] = report["quarantined"]
    assert quar["cluster"] == "vanished"
    assert quar["error"]["code"] == "E_SOURCE"
    assert quar["source"].startswith("unreadable-")


def test_bench_campaign_contract(no_checkpoint):
    """The fleet path's bench tag: clusters/sec > 0, quarantine count in
    the line (the bench-regress series exists from day one)."""
    import bench

    dt, report, label = bench.run_campaign_bench(2, 4, 8)
    assert dt > 0 and label.startswith("campaign2c")
    assert report["totals"]["quarantined"] == 0
    assert report["totals"]["completed"] == 2
