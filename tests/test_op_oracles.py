"""Differential tests: tensor ops vs straightforward numpy oracles.

The reference has no per-op tests (its one integration test covers the
vendored scheduler); SURVEY.md section 4 calls for adding these in the
rebuild — random instances, independently recomputed expectations.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.ops import filters, scores
from open_simulator_tpu.ops.domains import domain_count, domain_min, same_domain


def random_topology(rng, n, d):
    """one-hot [1, N, D] + per-node domain ids (some nodes lack the key)."""
    ids = rng.randint(-1, d, size=n)
    onehot = np.zeros((1, n, d), dtype=np.float32)
    for i, v in enumerate(ids):
        if v >= 0:
            onehot[0, i, v] = 1.0
    return onehot, ids


@pytest.mark.parametrize("seed", range(5))
def test_domain_count_oracle(seed):
    rng = np.random.RandomState(seed)
    n, d = 17, 5
    onehot, ids = random_topology(rng, n, d)
    counts = rng.randint(0, 7, size=n).astype(np.float32)

    # hostname key (id 0): identity
    np.testing.assert_allclose(
        np.asarray(domain_count(jnp.asarray(counts), 0, jnp.asarray(onehot))), counts
    )
    # zone-like key (id 1)
    got = np.asarray(domain_count(jnp.asarray(counts), 1, jnp.asarray(onehot)))
    want = np.zeros(n, dtype=np.float32)
    for i in range(n):
        if ids[i] >= 0:
            want[i] = sum(counts[j] for j in range(n) if ids[j] == ids[i])
    np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("seed", range(5))
def test_domain_min_oracle(seed):
    rng = np.random.RandomState(seed)
    n, d = 13, 4
    onehot, ids = random_topology(rng, n, d)
    counts = rng.randint(0, 9, size=n).astype(np.float32)
    eligible = rng.rand(n) > 0.3

    got, _ = domain_min(jnp.asarray(counts), 1, jnp.asarray(onehot), jnp.asarray(eligible))
    elig_domains = {ids[i] for i in range(n) if eligible[i] and ids[i] >= 0}
    if elig_domains:
        want = min(sum(counts[j] for j in range(n) if ids[j] == dom) for dom in elig_domains)
    else:
        # nodes without the key can still be eligible -> min over eligible... the
        # op returns 0.0 only when NO node is eligible at all
        want = float(np.asarray(got)) if eligible.any() else 0.0
    if elig_domains:
        assert float(got) == want
    # hostname variant
    got_h, _ = domain_min(jnp.asarray(counts), 0, jnp.asarray(onehot), jnp.asarray(eligible))
    if eligible.any():
        assert float(got_h) == counts[eligible].min()


def test_same_domain_oracle():
    rng = np.random.RandomState(0)
    n, d = 11, 3
    onehot, ids = random_topology(rng, n, d)
    node = 4
    got = np.asarray(same_domain(node, 1, jnp.asarray(onehot), n))
    want = np.array([1.0 if ids[i] == ids[node] and ids[i] >= 0 else 0.0 for i in range(n)],
                    dtype=np.float32)
    if ids[node] < 0:
        want = np.zeros(n, dtype=np.float32)
    np.testing.assert_allclose(got, want)
    got_h = np.asarray(same_domain(node, 0, jnp.asarray(onehot), n))
    assert got_h[node] == 1.0 and got_h.sum() == 1.0


@pytest.mark.parametrize("seed", range(3))
def test_fit_oracle(seed):
    rng = np.random.RandomState(seed)
    n, r = 9, 4
    alloc = rng.randint(0, 100, size=(n, r)).astype(np.float32)
    used = (alloc * rng.rand(n, r) * 1.2).astype(np.float32)
    req = rng.randint(0, 30, size=r).astype(np.float32)
    got = np.asarray(filters.fit_per_resource(jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req)))
    want = used + req[None, :] <= alloc
    np.testing.assert_array_equal(got, want)


def test_least_allocated_oracle():
    alloc = np.array([[4000, 8192], [2000, 4096]], dtype=np.float32)
    used = np.array([[1000, 2048], [0, 0]], dtype=np.float32)
    req = np.array([500, 1024], dtype=np.float32)
    got = np.asarray(scores.least_allocated_score(
        jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))
    # node0: cpu free (4000-1500)/4000=0.625, mem (8192-3072)/8192=0.625 -> 62.5
    # node1: cpu 0.75, mem 0.75 -> 75
    np.testing.assert_allclose(got, [62.5, 75.0], rtol=1e-5)


def test_balanced_allocation_oracle():
    alloc = np.array([[4000, 8192]], dtype=np.float32)
    used = np.array([[0, 0]], dtype=np.float32)
    req = np.array([2000, 2048], dtype=np.float32)
    got = float(np.asarray(scores.balanced_allocation_score(
        jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))[0])
    fr = np.array([2000 / 4000, 2048 / 8192])
    want = (1 - fr.std()) * 100
    assert abs(got - want) < 1e-3


def test_simon_max_share_oracle():
    # share(req, alloc-req) per resource, max, min-max normalized over feasible
    alloc = np.array([[4000, 8192, 0, 110], [8000, 8192, 0, 110]], dtype=np.float32)
    req = np.array([2000, 2048, 0, 1], dtype=np.float32)
    feas = np.array([True, True])
    got = np.asarray(scores.simon_max_share_score(jnp.asarray(alloc), jnp.asarray(req), jnp.asarray(feas)))

    def raw(alloc_row):
        shares = []
        for a, r in zip(alloc_row, req):
            t = a - r
            shares.append((1.0 if r else 0.0) if t == 0 else min(max(r / t, 0), 1) if t > 0 else 1.0)
        return max(shares) * 100

    raws = np.array([raw(alloc[0]), raw(alloc[1])])
    lo, hi = raws.min(), raws.max()
    want = (raws - lo) * 100 / (hi - lo)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_minmax_and_max_normalize_edges():
    feas = jnp.asarray([True, True, False])
    raw = jnp.asarray([5.0, 5.0, 99.0])
    out = np.asarray(scores.minmax_normalize(raw, feas))
    np.testing.assert_allclose(out, [0.0, 0.0, 0.0])  # zero range -> 0, infeasible -> 0
    out2 = np.asarray(scores.max_normalize(jnp.asarray([0.0, 0.0, 0.0]), feas, reverse=True))
    np.testing.assert_allclose(out2[:2], [100.0, 100.0])  # no taints anywhere -> all max


@pytest.mark.parametrize("seed", range(3))
def test_topology_spread_score_oracle(seed):
    # vendored two-pass: raw = sum_c domain-count * log(#domains_c + 2) over
    # soft constraints; normalize 100*(max+min-raw)/max over feasible nodes
    rng = np.random.RandomState(seed)
    n, d, s = 11, 3, 4
    onehot, ids = random_topology(rng, n, d)
    group_count = rng.randint(0, 5, size=(n, s)).astype(np.float32)
    has_key = np.ones((2, n), dtype=np.float32)
    active = np.ones(n, dtype=bool)
    feasible = rng.rand(n) > 0.2
    if not feasible.any():
        feasible[0] = True
    spread_group = np.array([rng.randint(0, s), rng.randint(0, s)], dtype=np.int32)
    spread_key = np.array([0, 1], dtype=np.int32)      # hostname + zone
    spread_hard = np.array([False, False])
    spread_valid = np.array([True, True])

    got = np.asarray(scores.topology_spread_score(
        jnp.asarray(group_count), jnp.asarray(onehot), jnp.asarray(has_key),
        jnp.asarray(active), jnp.asarray(spread_group), jnp.asarray(spread_key),
        jnp.asarray(spread_hard), jnp.asarray(spread_valid), jnp.asarray(feasible),
    ))

    # numpy oracle
    n_domains = [float(n), float(len({v for v in ids if v >= 0}))]
    raw = np.zeros(n)
    for c in range(2):
        vec = group_count[:, spread_group[c]]
        if spread_key[c] == 0:
            dc = vec
        else:
            per_dom = onehot[0].T @ vec
            dc = onehot[0] @ per_dom
        raw += dc * np.log(n_domains[spread_key[c]] + 2.0)
    mx = raw[feasible].max()
    mn = raw[feasible].min()
    want = 100.0 * (mx + mn - raw) / max(mx, 1e-9) if mx > 0 else np.full(n, 100.0)
    want = np.where(feasible, want, 0.0)
    np.testing.assert_allclose(got, want, rtol=2e-4)


def test_topology_spread_score_hard_constraints_excluded():
    # DoNotSchedule constraints do not contribute to the score (vendored
    # PreScore filters to ScheduleAnyway)
    n, d, s = 5, 2, 1
    onehot = np.zeros((1, n, d), dtype=np.float32)
    group_count = np.arange(n, dtype=np.float32).reshape(n, 1)
    got = np.asarray(scores.topology_spread_score(
        jnp.asarray(group_count), jnp.asarray(onehot),
        jnp.ones((2, n), dtype=np.float32), jnp.ones(n, dtype=bool),
        jnp.array([0], dtype=np.int32), jnp.array([0], dtype=np.int32),
        jnp.array([True]), jnp.array([True]), jnp.ones(n, dtype=bool),
    ))
    np.testing.assert_allclose(got, np.zeros(n))


def test_topology_spread_score_ignores_nodes_missing_key():
    # vendored IgnoredNodes: a node without the constraint's topology key
    # scores 0, not best
    n, d = 4, 2
    onehot = np.zeros((1, n, d), dtype=np.float32)
    onehot[0, 0, 0] = onehot[0, 1, 0] = onehot[0, 2, 1] = 1.0  # node 3 lacks key
    has_key = np.ones((2, n), dtype=np.float32)
    has_key[1, 3] = 0.0
    group_count = np.array([[2.0], [2.0], [1.0], [0.0]])
    got = np.asarray(scores.topology_spread_score(
        jnp.asarray(group_count), jnp.asarray(onehot), jnp.asarray(has_key),
        jnp.ones(n, dtype=bool),
        jnp.array([0], dtype=np.int32), jnp.array([1], dtype=np.int32),
        jnp.array([False]), jnp.array([True]), jnp.ones(n, dtype=bool),
    ))
    assert got[3] == 0.0
    assert got[2] > got[0] == got[1] > 0.0


def test_topology_spread_score_max_skew_shift():
    # scoreForCount adds maxSkew-1 to raw before the normalize pass
    # (podtopologyspread/scoring.go:292); the (max+min-raw)/max pass is not
    # shift-invariant, so maxSkew > 1 must change the normalized scores.
    n, d = 4, 2
    onehot = np.zeros((1, n, d), dtype=np.float32)
    onehot[0, 0, 0] = onehot[0, 1, 0] = onehot[0, 2, 1] = onehot[0, 3, 1] = 1.0
    group_count = np.array([[3.0], [3.0], [1.0], [1.0]], dtype=np.float32)

    def run(skew):
        return np.asarray(scores.topology_spread_score(
            jnp.asarray(group_count), jnp.asarray(onehot),
            jnp.ones((2, n), dtype=np.float32), jnp.ones(n, dtype=bool),
            jnp.array([0], dtype=np.int32), jnp.array([1], dtype=np.int32),
            jnp.array([False]), jnp.array([True]), jnp.ones(n, dtype=bool),
            spread_skew=jnp.array([skew], dtype=np.float32),
        ))

    # numpy oracle: dc = per-domain matching totals, w = log(#domains + 2)
    w = np.log(2 + 2.0)
    for skew in (1.0, 5.0):
        raw = np.array([6.0, 6.0, 2.0, 2.0]) * w + (skew - 1.0)
        mx, mn = raw.max(), raw.min()
        want = 100.0 * (mx + mn - raw) / mx
        np.testing.assert_allclose(run(skew), want, rtol=2e-4)
    assert run(5.0)[0] > run(1.0)[0]  # the shift waters down the spread penalty
