"""Differential tests: tensor ops vs straightforward numpy oracles.

The reference has no per-op tests (its one integration test covers the
vendored scheduler); SURVEY.md section 4 calls for adding these in the
rebuild — random instances, independently recomputed expectations.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.ops import filters, scores
from open_simulator_tpu.ops.domains import domain_count, domain_min, same_domain


def random_topology(rng, n, d):
    """one-hot [1, N, D] + per-node domain ids (some nodes lack the key)."""
    ids = rng.randint(-1, d, size=n)
    onehot = np.zeros((1, n, d), dtype=np.float32)
    for i, v in enumerate(ids):
        if v >= 0:
            onehot[0, i, v] = 1.0
    return onehot, ids


@pytest.mark.parametrize("seed", range(5))
def test_domain_count_oracle(seed):
    rng = np.random.RandomState(seed)
    n, d = 17, 5
    onehot, ids = random_topology(rng, n, d)
    counts = rng.randint(0, 7, size=n).astype(np.float32)

    # hostname key (id 0): identity
    np.testing.assert_allclose(
        np.asarray(domain_count(jnp.asarray(counts), 0, jnp.asarray(onehot))), counts
    )
    # zone-like key (id 1)
    got = np.asarray(domain_count(jnp.asarray(counts), 1, jnp.asarray(onehot)))
    want = np.zeros(n, dtype=np.float32)
    for i in range(n):
        if ids[i] >= 0:
            want[i] = sum(counts[j] for j in range(n) if ids[j] == ids[i])
    np.testing.assert_allclose(got, want)


@pytest.mark.parametrize("seed", range(5))
def test_domain_min_oracle(seed):
    rng = np.random.RandomState(seed)
    n, d = 13, 4
    onehot, ids = random_topology(rng, n, d)
    counts = rng.randint(0, 9, size=n).astype(np.float32)
    eligible = rng.rand(n) > 0.3

    got, _ = domain_min(jnp.asarray(counts), 1, jnp.asarray(onehot), jnp.asarray(eligible))
    elig_domains = {ids[i] for i in range(n) if eligible[i] and ids[i] >= 0}
    if elig_domains:
        want = min(sum(counts[j] for j in range(n) if ids[j] == dom) for dom in elig_domains)
    else:
        # nodes without the key can still be eligible -> min over eligible... the
        # op returns 0.0 only when NO node is eligible at all
        want = float(np.asarray(got)) if eligible.any() else 0.0
    if elig_domains:
        assert float(got) == want
    # hostname variant
    got_h, _ = domain_min(jnp.asarray(counts), 0, jnp.asarray(onehot), jnp.asarray(eligible))
    if eligible.any():
        assert float(got_h) == counts[eligible].min()


def test_same_domain_oracle():
    rng = np.random.RandomState(0)
    n, d = 11, 3
    onehot, ids = random_topology(rng, n, d)
    node = 4
    got = np.asarray(same_domain(node, 1, jnp.asarray(onehot), n))
    want = np.array([1.0 if ids[i] == ids[node] and ids[i] >= 0 else 0.0 for i in range(n)],
                    dtype=np.float32)
    if ids[node] < 0:
        want = np.zeros(n, dtype=np.float32)
    np.testing.assert_allclose(got, want)
    got_h = np.asarray(same_domain(node, 0, jnp.asarray(onehot), n))
    assert got_h[node] == 1.0 and got_h.sum() == 1.0


@pytest.mark.parametrize("seed", range(3))
def test_fit_oracle(seed):
    """The headroom-form fit must equal the vendored `used + req <= alloc`
    (fit.go fitsRequest). Integer-valued quantities (the encoder's units)
    keep both forms bit-exact; used may exceed alloc (forced overcommit)."""
    rng = np.random.RandomState(seed)
    n, r = 9, 4
    alloc = rng.randint(0, 100, size=(n, r)).astype(np.float32)
    used = rng.randint(0, 120, size=(n, r)).astype(np.float32)
    req = rng.randint(0, 30, size=r).astype(np.float32)
    got = np.asarray(filters.fit_per_resource(jnp.asarray(alloc - used), jnp.asarray(req)))
    want = used + req[None, :] <= alloc
    np.testing.assert_array_equal(got, want)


def test_least_allocated_oracle():
    alloc = np.array([[4000, 8192], [2000, 4096]], dtype=np.float32)
    used = np.array([[1000, 2048], [0, 0]], dtype=np.float32)
    req = np.array([500, 1024], dtype=np.float32)
    got = np.asarray(scores.least_allocated_score(
        jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))
    # node0: cpu free (4000-1500)/4000=0.625, mem (8192-3072)/8192=0.625 -> 62.5
    # node1: cpu 0.75, mem 0.75 -> 75
    np.testing.assert_allclose(got, [62.5, 75.0], rtol=1e-5)


def test_balanced_allocation_oracle():
    alloc = np.array([[4000, 8192]], dtype=np.float32)
    used = np.array([[0, 0]], dtype=np.float32)
    req = np.array([2000, 2048], dtype=np.float32)
    got = float(np.asarray(scores.balanced_allocation_score(
        jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))[0])
    fr = np.array([2000 / 4000, 2048 / 8192])
    want = (1 - fr.std()) * 100
    assert abs(got - want) < 1e-3


def test_simon_max_share_oracle():
    # share(req, alloc-req) per resource, max, min-max normalized over feasible
    alloc = np.array([[4000, 8192, 0, 110], [8000, 8192, 0, 110]], dtype=np.float32)
    req = np.array([2000, 2048, 0, 1], dtype=np.float32)
    feas = np.array([True, True])
    got = np.asarray(scores.simon_max_share_score(jnp.asarray(alloc), jnp.asarray(req), jnp.asarray(feas)))

    def raw(alloc_row):
        shares = []
        for a, r in zip(alloc_row, req):
            t = a - r
            shares.append((1.0 if r else 0.0) if t == 0 else min(max(r / t, 0), 1) if t > 0 else 1.0)
        return max(shares) * 100

    raws = np.array([raw(alloc[0]), raw(alloc[1])])
    lo, hi = raws.min(), raws.max()
    want = (raws - lo) * 100 / (hi - lo)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_minmax_and_max_normalize_edges():
    feas = jnp.asarray([True, True, False])
    raw = jnp.asarray([5.0, 5.0, 99.0])
    out = np.asarray(scores.minmax_normalize(raw, feas))
    np.testing.assert_allclose(out, [0.0, 0.0, 0.0])  # zero range -> 0, infeasible -> 0
    out2 = np.asarray(scores.max_normalize(jnp.asarray([0.0, 0.0, 0.0]), feas, reverse=True))
    np.testing.assert_allclose(out2[:2], [100.0, 100.0])  # no taints anywhere -> all max


# (The standalone topology_spread_score op and its oracles moved: the scan
# engine inlines spread pass 1; the live inline path is oracle-tested end to
# end in tests/test_engine_spread_oracle.py.)


@pytest.mark.parametrize("seed", range(4))
def test_hoist_active_stats_oracle(seed):
    """ActiveHoist vs a direct numpy recount: domains-with-an-active-member
    per key, per-class eligibility, and the hoisted log weights."""
    from open_simulator_tpu.ops.domains import hoist_active_stats

    rng = np.random.RandomState(seed)
    n, d, c = 13, 4, 3
    onehot, ids = random_topology(rng, n, d)
    has_key = np.ones((2, n), dtype=np.float32)
    has_key[1] = (ids >= 0).astype(np.float32)
    class_aff = rng.rand(c, n) > 0.3
    active = rng.rand(n) > 0.25

    h = hoist_active_stats(
        jnp.asarray(onehot), jnp.asarray(has_key), jnp.asarray(class_aff),
        jnp.asarray(active))

    want_dom = [float(active.sum()),
                float(len({ids[i] for i in range(n) if active[i] and ids[i] >= 0}))]
    np.testing.assert_allclose(np.asarray(h.dom_counts), want_dom)
    np.testing.assert_allclose(np.asarray(h.log_dom), np.log(np.array(want_dom) + 2.0))

    elig = class_aff & active[None, :] & (has_key[None, 1] > 0)  # key 1
    for ci in range(c):
        want_has = np.zeros(d, dtype=bool)
        for i in range(n):
            if elig[ci, i] and ids[i] >= 0:
                want_has[ids[i]] = True
        np.testing.assert_array_equal(np.asarray(h.domain_has)[ci, 0], want_has)
        # hostname eligibility ignores has_key (every node is its own domain)
        np.testing.assert_array_equal(
            np.asarray(h.elig_host)[ci], class_aff[ci] & active)
    np.testing.assert_array_equal(
        np.asarray(h.any_elig)[:, 0], (class_aff & active[None, :]).any(axis=1))
    np.testing.assert_array_equal(np.asarray(h.any_elig)[:, 1], elig.any(axis=1))


@pytest.mark.parametrize("seed", range(4))
def test_domain_min_hoisted_oracle(seed):
    """domain_min_hoisted vs a recount of the vendored minMatchNum: min of
    per-domain totals over domains holding an eligible node."""
    from open_simulator_tpu.ops.domains import domain_min_hoisted, hoist_active_stats

    rng = np.random.RandomState(seed + 100)
    n, d = 11, 3
    onehot, ids = random_topology(rng, n, d)
    has_key = np.ones((2, n), dtype=np.float32)
    class_aff = (rng.rand(1, n) > 0.3)
    active = rng.rand(n) > 0.2
    counts = rng.randint(0, 6, size=n).astype(np.float32)

    h = hoist_active_stats(
        jnp.asarray(onehot), jnp.asarray(has_key), jnp.asarray(class_aff),
        jnp.asarray(active))
    got = float(domain_min_hoisted(
        jnp.asarray(counts), 1, 0, jnp.asarray(onehot), h))

    elig = class_aff[0] & active
    elig_domains = {ids[i] for i in range(n) if elig[i] and ids[i] >= 0}
    if elig.any():
        if elig_domains:
            want = min(
                sum(counts[j] for j in range(n) if ids[j] == dom)
                for dom in elig_domains
            )
            assert got == want
    else:
        assert got == 0.0
    # hostname: min over eligible nodes' own counts
    got_h = float(domain_min_hoisted(jnp.asarray(counts), 0, 0, jnp.asarray(onehot), h))
    if elig.any():
        assert got_h == counts[elig].min()
    else:
        assert got_h == 0.0


@pytest.mark.parametrize("seed", range(4))
def test_resource_scores_fused_matches_component_ops(seed):
    """The scan engine's fused Balanced+Least+Most must match the three
    component score ops (which are themselves oracle-tested above)."""
    rng = np.random.RandomState(seed)
    n, r = 12, 4
    alloc = rng.randint(1, 100, size=(n, r)).astype(np.float32)
    alloc[0, 0] = 0.0  # cap<=0: headroom-form convention checked separately
    used = (alloc * rng.rand(n, r)).astype(np.float32)
    req = rng.randint(0, 30, size=r).astype(np.float32)
    inv = np.where(alloc > 0, 1.0 / np.where(alloc > 0, alloc, 1.0), 0.0)
    for wb, wl, wm in [(1.0, 1.0, 0.0), (1.0, 0.0, 2.0), (0.5, 1.5, 1.0)]:
        got = np.asarray(scores.resource_scores_fused(
            jnp.asarray(alloc - used), jnp.asarray(inv),
            jnp.asarray(req), (0, 1), wb, wl, wm))
        want = (
            wb * np.asarray(scores.balanced_allocation_score(
                jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))
            + wl * np.asarray(scores.least_allocated_score(
                jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))
            + wm * np.asarray(scores.most_allocated_score(
                jnp.asarray(used), jnp.asarray(alloc), jnp.asarray(req), (0, 1)))
        )
        # row 0 has a zero-capacity cpu: Least (h=0 -> 0 free) and Most
        # (masked to 0 by inv_alloc > 0, like mostRequestedScore's
        # capacity==0 early-out) agree with the component ops; only
        # Balanced diverges there (component reads 0% utilized, headroom
        # form 0% free) — compare healthy rows to the oracle and row 0 to
        # the headroom-form expectation
        np.testing.assert_allclose(got[1:], want[1:], rtol=1e-4, atol=1e-3)
        h_m0 = (alloc[0, 1] - used[0, 1] - req[1]) * inv[0, 1]
        want0 = (
            wb * (1.0 - abs(0.0 - h_m0) * 0.5) * 100.0
            + wl * (max(h_m0, 0.0) * 50.0)
            + wm * ((0.0 + min(max(1.0 - h_m0, 0.0), 1.0)) * 50.0)
        )
        np.testing.assert_allclose(got[0], want0, rtol=1e-4, atol=1e-3)
