"""v1beta2 system-default topology spread (soft) for workload pods."""

from collections import Counter

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.testing import make_fake_deployment, make_fake_node


def test_workload_pods_default_spread_across_zones():
    # Two zones with unequal node counts; without the default soft spread,
    # bin-packing scores would favor piling into one zone.
    nodes = [
        make_fake_node("a0", cpu="16", memory="32Gi",
                       labels={"topology.kubernetes.io/zone": "za"}),
        make_fake_node("a1", cpu="16", memory="32Gi",
                       labels={"topology.kubernetes.io/zone": "za"}),
        make_fake_node("b0", cpu="16", memory="32Gi",
                       labels={"topology.kubernetes.io/zone": "zb"}),
    ]
    cluster = ClusterResources()
    cluster.nodes = nodes
    app = ClusterResources()
    app.deployments = [make_fake_deployment("web", replicas=6, cpu="100m", memory="128Mi")]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert not res.unscheduled_pods
    zones = Counter("z" + sp.node_name[0] for sp in res.scheduled_pods)
    # soft default (zone maxSkew 3): both zones must be used
    assert zones["za"] >= 2 and zones["zb"] >= 2
    # hostname default (maxSkew 5): all nodes used
    hosts = {sp.node_name for sp in res.scheduled_pods}
    assert hosts == {"a0", "a1", "b0"}
