"""GL5 fixture: the compact-carry bf16 promotion hazard.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SimState(NamedTuple):
    headroom: object     # always f32
    group_count: object  # bf16 | f32 depending on compact_carry


def init_state(arrs, cfg):
    f32 = jnp.float32
    cdt = jnp.bfloat16 if cfg.compact_carry else f32
    return SimState(
        headroom=jnp.zeros((4, 2), f32),
        group_count=jnp.zeros((4, 3), cdt),
    )


def _step(state, x):
    paint = x["match"]
    headroom = state.headroom + paint  # ok: dtype is unconditionally f32
    guarded = state.group_count + paint.astype(state.group_count.dtype)  # ok
    bad = state.group_count + paint  # GL5: silent bf16 -> f32 promotion
    return SimState(headroom=headroom, group_count=bad + guarded * 0), headroom


def run(arrs, cfg, xs):
    return jax.lax.scan(_step, init_state(arrs, cfg), xs)
