"""GL4 fixture: the host-sync catalog inside jit/scan scope.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("mode",))
def kernel(a, b, mode):
    if mode == "fast":  # ok: `mode` is a declared static argname
        b = b * 2.0
    if a.sum() > 0:  # GL4: Python `if` on a traced value
        b = b + 1.0
    while b.max() > 1.0:  # GL4: Python `while` on a traced value
        b = b * 0.5
    n = float(jnp.sum(a))  # GL4: float() host conversion
    h = a.item()  # GL4: .item() host sync
    w = np.asarray(b)  # GL4: numpy call on a traced value
    for i in range(jnp.argmax(a)):  # GL4: loop bound from a traced value
        n = n + i
    for kk in range(a.shape[0]):  # ok: shapes are static
        n = n + kk
    return b + n + h + w


def _step(state, x):
    if x["flag"]:  # GL4: `if` on a traced xs leaf inside the scan step
        state = state + 1.0
    return state, state


def run(xs):
    return jax.lax.scan(_step, jnp.zeros(()), xs)
