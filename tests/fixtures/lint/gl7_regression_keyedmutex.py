"""GL7 regression fixture: the PR-11 session-store deadlock.

Eviction held one session's key and then *blocking*-acquired a second
key of the same KeyedMutex while rehydration did the opposite — the
classic AB-BA interleave. Two threads evicting A->B and B->A deadlock.
The shipped fix switched the second acquire to try_hold; this fixture
keeps the broken blocking shape and must flag GL7.
"""

from open_simulator_tpu.resilience.lifecycle import KeyedMutex


class SessionStore:
    def __init__(self):
        self._mu = KeyedMutex()
        self._resident = {}

    def evict_into(self, victim, target):
        with self._mu.hold(victim):
            snap = self._resident.pop(victim, None)
            with self._mu.hold(target):  # blocking cross-key: AB-BA
                self._resident[target] = snap

    def rehydrate_from(self, target, victim):
        with self._mu.hold(target):
            with self._mu.hold(victim):  # opposite order on other thread
                self._resident[target] = self._resident.get(victim)
