"""GL4 fixture (clean): the SAFE pattern for host-side executable-cache
bookkeeping next to jit scope (companion to gl4_telemetry_ok.py).

The exec-cache layer (engine/exec_cache.py) keeps an LRU of AOT-compiled
executables. All of its bookkeeping — dict lookups, LRU reordering,
hit/miss counting, compile timing — is HOST control flow on HOST values
(string/shape keys, Python ints), never on traced arrays: the key is
derived from static `.shape`/`.dtype` metadata BEFORE the jit boundary,
the `if key in cache` branch runs outside any trace, and the traced body
stays pure jnp. This file must produce ZERO findings; the negative
example (branching on a traced value / .item() inside jit) lives in
gl4_trace.py.
"""

import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from open_simulator_tpu.telemetry import counter

_CACHE = OrderedDict()
_CAPACITY = 2


def _traced_sum(xs, scale):
    # traced scope: pure jnp math — no cache reads, no metrics, no host
    # branches on traced values
    return jnp.sum(xs) * scale


def run_cached(values, scale=2.0):
    xs = jnp.asarray(values)
    # cache key from STATIC metadata (shape/dtype are host values even on
    # a traced array; reading them is not a device sync)
    key = (tuple(xs.shape), str(xs.dtype), float(scale))
    compiled = _CACHE.get(key)
    if compiled is None:  # host branch on a host value: safe
        counter("fixture_exec_cache_total",
                labelnames=("event",)).labels(event="miss").inc()
        t0 = time.perf_counter()
        compiled = jax.jit(_traced_sum).lower(xs, scale).compile()
        counter("fixture_exec_compiles_total").inc()
        _ = time.perf_counter() - t0  # host timing of the compile, host-side
        _CACHE[key] = compiled
        while len(_CACHE) > _CAPACITY:
            _CACHE.popitem(last=False)
            counter("fixture_exec_cache_total",
                    labelnames=("event",)).labels(event="eviction").inc()
    else:
        counter("fixture_exec_cache_total",
                labelnames=("event",)).labels(event="hit").inc()
        _CACHE.move_to_end(key)
    out = compiled(xs, scale)
    return float(np.asarray(out))  # device -> host OUTSIDE the jit
