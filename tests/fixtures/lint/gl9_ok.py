"""GL9 fixture (clean): durable writes ride the storage fault domain.

(The `gl9_` filename prefix opts this file into GL9's path scope, which
in the product tree covers resilience/, telemetry/, campaign/ and
replay/.)

  * the closure-handoff shape: the write is defined locally and handed
    to `faults.run_io`, which owns retries and the ENOSPC/EIO rung;
  * a DurableJournal subclass writing directly — the journal IS the
    sanctioned owner of frames and fsyncs;
  * read-mode opens, which are never durable writes.

This file must produce ZERO findings under every rule.
"""

import json
import os

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.resilience.journal import DurableJournal


def export_report(path, payload):
    def write():
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)

    faults.run_io("fixture_export", write)
    return path


class FixtureJournal(DurableJournal):
    def flush_frame(self, frame):
        # the journal owns its framing + fsync discipline
        with open(self._path, "a", encoding="utf-8") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())


def load_report(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
