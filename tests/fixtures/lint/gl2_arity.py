"""GL2 fixture: partial-into-scan arity broken three ways.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import functools

import jax
import jax.numpy as jnp


def _step(table, weight, state, x):
    return state + table[x] * weight, state


def run_underbound(xs):
    step = functools.partial(_step, jnp.ones((4,)))  # binds 1, needs 2
    return jax.lax.scan(step, jnp.zeros(()), xs)


def run_overbound(xs):
    step = functools.partial(_step, 1.0, 2.0, 3.0)  # binds 3, one too many
    return jax.lax.scan(step, jnp.zeros(()), xs)


def run_bad_keyword(xs):
    step = functools.partial(_step, 1.0, weight=2.0, gain=3.0)  # no `gain`
    return jax.lax.scan(step, jnp.zeros(()), xs)
