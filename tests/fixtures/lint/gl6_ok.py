"""GL6 fixture (clean): every device dispatch rides the fault domain.

The four sanctioned shapes, one per function below:

  (a) dispatch inside a wrapper's argument subtree (thunk or lambda),
      including through an *aliased* import of the wrapper;
  (b) a named closure handed to the wrapper after its def;
  (c) a callee that owns the domain internally, called bare;
  (d) dispatch from inside a traced (jit) function — the traced invoker
      carries its own wrapper at its call site.

This file must produce ZERO findings under every rule; the negative
example (the PR-14 unwrapped block) lives in
gl6_regression_unwrapped.py.
"""

import jax
import jax.numpy as jnp

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.resilience.faults import run_launch as rl


def wrapped_thunk(state):
    # (a) the canonical shape: the dispatch is the wrapper's argument
    return faults.run_launch("batched_schedule",
                             lambda: batched_schedule(state))


def wrapped_via_alias(out):
    # (a) through an import alias: `rl` still resolves to run_launch
    return rl("sync", lambda: out.block_until_ready())


def closure_handoff(state):
    # (b) the def precedes the wrapper call; the name is still sanctioned
    def launch():
        return schedule_pods(state)

    return faults.run_launch("schedule_pods", launch)


def run_batched_cached(state):
    # (c) callee-owns-the-domain: the wrapper lives inside this def, so a
    # bare `run_batched_cached(...)` call site (below) is fine
    return faults.run_launch("batched", lambda: batched_schedule(state))


def bare_call_to_domain_owner(state):
    return run_batched_cached(state)


@jax.jit
def schedule_pods(xs):
    # (d) traced body: dispatch happens at the traced invoker's site
    return jnp.sum(xs)


def batched_schedule(state):
    return schedule_pods(jnp.asarray(state))
