"""GL10 fixture (clean): every used metric name resolves to a family.

Families are declared two sanctioned ways — a string literal first
argument, and the module-constant convention
(`FAMILY = "simon_..."` handed to the constructor). Consumers may also
address a family by prefix (ledger greps do). This file must produce
ZERO findings under every rule.
"""

from open_simulator_tpu.telemetry import counter, histogram

FIXTURE_SECONDS = "simon_fixture_seconds"


def declare():
    return (
        counter("simon_fixture_runs_total", "fixture runs", labelnames=("kind",)),
        histogram(FIXTURE_SECONDS, "fixture wall time"),
    )


def record(registry, dur):
    runs, seconds = declare()
    runs.labels(kind="ok").inc()
    seconds.observe(dur)
    # prefix addressing (how the run ledger greps a family's series)
    return registry.collect("simon_fixture")
