"""GL4 fixture (clean): the SAFE pattern for mesh-sharded AOT cache
bookkeeping (companion to gl4_execcache_ok.py, which covers the
single-device cache).

The mesh path of the executable cache (engine/exec_cache.py
run_mesh_cached) adds two things on top of the single-device LRU, both
of which must stay HOST control flow on HOST values:

* the lane function is built ONCE at module level (lru_cache on static
  config) — never a fresh `jit(vmap(lambda ...))` per call, the shape
  GL6 rejects in gl6_regression_percall_vmap.py;
* the cache key extends with the mesh AXIS SPLIT and device ids —
  strings and ints read from mesh metadata BEFORE the jit boundary, so
  the `if key in cache` branch never touches a traced value and a
  different mesh split can never collide with a compiled executable for
  another split.

Sharding objects (NamedSharding/PartitionSpec) are host metadata too:
constructing them and passing them to in_shardings/out_shardings is not
device work. This file must produce ZERO findings; the traced body
stays pure jnp.
"""

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from open_simulator_tpu.telemetry import counter

_CACHE = OrderedDict()
_CAPACITY = 2


@functools.lru_cache(maxsize=8)
def _lane_fn(scale):
    # built once per static config (scale is hashable host data): the
    # SAME traced program backs every mesh split, so digests agree
    def lane(xs, mask):
        # traced scope: pure jnp math — no cache reads, no metrics, no
        # host branches on traced values
        return jnp.sum(xs * mask) * scale

    return jax.vmap(lane, in_axes=(None, 0))


def run_mesh_cached(values, masks, mesh, scale=2.0):
    xs = jnp.asarray(values)
    ms = jnp.asarray(masks)
    # axis split + device ids are HOST metadata on the mesh object —
    # reading them is not a device sync, and keying on them keeps one
    # compiled executable per mesh shape
    axis_split = tuple((str(n), int(s)) for n, s in mesh.shape.items())
    devices = tuple(str(d) for d in mesh.devices.flat)
    key = (tuple(xs.shape), tuple(ms.shape), str(xs.dtype), float(scale),
           axis_split, devices)
    compiled = _CACHE.get(key)
    if compiled is None:  # host branch on a host value: safe
        counter("fixture_mesh_cache_total",
                labelnames=("event",)).labels(event="miss").inc()
        # sharding specs are host-side metadata; the lane axis shards
        # over "scenario", the payload replicates
        lane_sh = NamedSharding(mesh, P("scenario"))
        repl_sh = NamedSharding(mesh, P())
        xs = jax.device_put(xs, repl_sh)
        ms = jax.device_put(ms, lane_sh)
        compiled = jax.jit(
            _lane_fn(scale),
            in_shardings=(repl_sh, lane_sh),
            out_shardings=lane_sh,
        ).lower(xs, ms).compile()
        _CACHE[key] = compiled
        while len(_CACHE) > _CAPACITY:
            _CACHE.popitem(last=False)
            counter("fixture_mesh_cache_total",
                    labelnames=("event",)).labels(event="eviction").inc()
    else:
        counter("fixture_mesh_cache_total",
                labelnames=("event",)).labels(event="hit").inc()
        _CACHE.move_to_end(key)
    out = compiled(xs, ms)
    return np.asarray(out)  # device -> host OUTSIDE the jit
