"""GL7 fixture (clean): the sanctioned locking patterns.

  * consistent acquisition ORDER across two module locks (A before B,
    everywhere) — edges but no cycle;
  * `try_hold` for the second key of a KeyedMutex — non-blocking by
    contract, so it is never a lock-order edge (the PR-11 fix);
  * snapshot-under-the-lock, launch-outside-it (the resident-cache
    _guard pattern);
  * a self-stored lock acquired through a helper method while the
    caller holds nothing.

This file must produce ZERO findings under every rule.
"""

import threading

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.resilience.lifecycle import KeyedMutex

_STATS_LOCK = threading.Lock()
_TABLE_LOCK = threading.Lock()
SESSIONS = KeyedMutex()


def ordered_everywhere(stats, table):
    # single documented order: _STATS_LOCK then _TABLE_LOCK
    with _STATS_LOCK:
        with _TABLE_LOCK:
            table.update(stats)


def same_order_elsewhere(table):
    with _STATS_LOCK:
        with _TABLE_LOCK:
            return dict(table)


def evict_then_rehydrate(src, dst):
    # PR-11 fix shape: the second key is try_hold (non-blocking), so no
    # cross-key blocking edge exists
    with SESSIONS.hold(src):
        with SESSIONS.try_hold(dst) as got:
            if not got:
                return False
    return True


def snapshot_then_launch(state):
    # snapshot under the lock, dispatch outside it
    with _STATS_LOCK:
        snap = dict(state)
    return faults.run_launch("batched", lambda: batched_schedule(snap))


def batched_schedule(snap):
    return faults.run_launch("inner", lambda: len(snap))


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def _locked_push(self, item):
        # helper owns the acquisition; callers hold nothing
        with self._lock:
            self._items.append(item)

    def add(self, item):
        self._locked_push(item)
