"""GL8 regression fixture: the PR-12 drifted status table.

rest.py once carried its own copy of the code->status mapping. It
drifted: serving.py learned E_BUSY -> 429 for the admission queue, the
copy still said 400, and load-shed clients saw "bad request" instead of
"retry later". The literal table below reproduces that exact drift and
must flag GL8 — the only legal home for the mapping is
serving.STATUS_BY_CODE.
"""

# the hand-copied map (note E_BUSY: the live table says 429)
_STATUS = {
    "E_VALIDATION": 400,
    "E_SOURCE": 400,
    "E_BUSY": 400,
    "E_BACKEND": 500,
}


def status_of(code):
    return _STATUS.get(code, 500)
