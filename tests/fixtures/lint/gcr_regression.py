"""Round-5 regression fixture: the exact bug shape PR 1 had to repair.

Round 5 landed a scan-scheduler refactor where (a) the step read
`x["gcr_gid"]` / `x["gcr_key"]` leaves that schedule_pods never encoded,
(b) a leaf was encoded that nothing consumed, (c) `functools.partial`
bound only 5 of the step's 8 parameters — and the tree imported clean,
silently breaking all 154 engine tests. graftlint must fail this shape
loudly: GL1 in both directions, GL2 on the arity.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import functools

import jax
import jax.numpy as jnp


class SnapshotArrays:
    req: object
    aff_group: object
    aff_key: object


def _pod_xs(arrs):
    names = [
        "req",
        "aff_group",
    ]
    xs = {k: getattr(arrs, k) for k in names}
    return xs


def _live_xs_names(cfg):
    live = {"req"}
    if cfg.enable_pod_affinity:
        live.add("aff_group")  # GL1: declared live, step reads gcr_* instead
    return live


def _step(arrs, active, cfg, hoisted, inv_alloc, gcr_seg, state, x):
    cols = jnp.take(state, x["gcr_gid"], axis=1)  # GL1a: never encoded
    keys = x["gcr_key"]  # GL1a: never encoded
    new_state = state + cols.sum() + keys.sum() + x["req"].sum()
    return new_state, new_state


def schedule_pods(arrs, active, cfg, hoisted, inv_alloc):
    xs = _pod_xs(arrs)
    xs["gcr_dead"] = arrs.aff_key  # GL1b: encoded but never read
    live = _live_xs_names(cfg)
    xs = {k: v for k, v in xs.items() if k in live}
    # the round-5 TypeError: 8-arg step with only 5 bound (missing gcr_seg)
    step = functools.partial(_step, arrs, active, cfg, hoisted, inv_alloc)
    return jax.lax.scan(step, jnp.zeros(()), xs)
