"""GL7 fixture (bad): the remaining lock hazards.

  * a lock-order CYCLE between two module locks (A->B here, B->A there);
  * self-nesting a non-reentrant threading.Lock (self-deadlock);
  * a plain lock held ACROSS a device launch — directly, and
    transitively through a helper call.
"""

import threading

from open_simulator_tpu.resilience import faults

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def forward(table):
    with LOCK_A:
        with LOCK_B:          # A -> B
            return dict(table)


def backward(table):
    with LOCK_B:
        with LOCK_A:          # B -> A: cycle with forward()
            return dict(table)


def double_acquire():
    with LOCK_A:
        with LOCK_A:          # non-reentrant self-nest: deadlock
            return True


def launch_under_lock(state):
    with LOCK_A:
        # the whole fleet stalls behind LOCK_A while the device retries
        return faults.run_launch("batched", lambda: sum(state))


def _helper_launch(state):
    return faults.run_launch("batched", lambda: sum(state))


def launch_under_lock_via_helper(state):
    with LOCK_B:
        return _helper_launch(state)   # transitive span through helper
