"""GL1 fixture: every direction of the xs-leaf contract broken at once.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import jax
import jax.numpy as jnp


class SnapshotArrays:
    req: object
    ports: object


def _pod_xs(arrs):
    names = [
        "req",
        "ports",
        "ghost_field",  # GL1c: not a SnapshotArrays field
    ]
    xs = {k: getattr(arrs, k) for k in names}
    return xs


def _step(state, x):
    fit = x["req"] + x["missing_leaf"]  # GL1a: read but never encoded
    return state + fit.sum(), fit


def run(arrs):
    xs = _pod_xs(arrs)
    xs["dead_leaf"] = arrs.ports  # GL1b: encoded but never read
    return jax.lax.scan(_step, jnp.zeros(()), xs)
