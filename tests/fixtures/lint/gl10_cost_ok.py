"""GL10 fixture (clean): the §20 cost-gauge family pattern.

Pins the idiom the executable cache and flight recorder use — literal
callback-gauge families (`simon_exec_cost_*` style, labeled by fn and
sampled only at render) alongside a module-constant counter family
with a bounded label set. GL10 must resolve every one of these to a
declaration. This file must produce ZERO findings under every rule.
"""

from open_simulator_tpu.telemetry import counter, gauge

TRACE_EVENTS_TOTAL = "simon_fixture_trace_events_total"


def declare(snapshot_fn):
    events = counter(TRACE_EVENTS_TOTAL, "fixture flight-recorder events",
                     labelnames=("kind",))

    def _field(field):
        return lambda: {(fn,): v[field] for fn, v in snapshot_fn().items()
                        if isinstance(v.get(field), (int, float))}

    flops = gauge("simon_fixture_cost_flops",
                  "fixture per-executable flop estimate",
                  labelnames=("fn",))
    # sampled only at render time — steady state pays nothing
    flops.set_callback(_field("flops"))
    hbm = gauge("simon_fixture_cost_peak_hbm_bytes",
                "fixture per-executable peak HBM estimate",
                labelnames=("fn",))
    hbm.set_callback(_field("peak_hbm_bytes"))
    return events, (flops, hbm)


def record(snapshot_fn):
    events, _gauges = declare(snapshot_fn)
    events.labels(kind="compile").inc()
