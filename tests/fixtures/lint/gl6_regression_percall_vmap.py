"""GL6 regression fixture: the pre-ISSUE-19 mesh-path compile leak.

`batched_schedule`'s mesh branch used to build a FRESH
`jit(vmap(lambda ...))` closure on every call and invoke it
immediately — so every bisect round recompiled the whole lane program
(seconds of XLA work per probe) and none of it ran inside the fault
domain. The immediate invoke of a jit result must flag GL6; the
sanctioned shape (module-level lane fn through the AOT cache, launched
via faults.run_cached_launch) lives in gl4_mesh_cache_ok.py.
"""

import jax
import jax.numpy as jnp


def _lane(arrs, mask, scale):
    return jnp.sum(arrs * mask) * scale


def sweep_round(arrs, masks, scale):
    # the leak: a fresh closure per call defeats jit's weak-ref cache,
    # and the immediate invoke dispatches outside the fault domain
    out = jax.jit(jax.vmap(lambda m: _lane(arrs, m, scale)))(masks)
    return out
