"""Control fixture: a miniature, fully-contract-honest scan module.

graftlint must report NOTHING here — every xs leaf is produced, live,
backed and consumed; the partial satisfies the step signature; no host
syncs; no conditional carry dtypes.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import functools

import jax
import jax.numpy as jnp


class SnapshotArrays:
    req: object
    ports: object


def _pod_xs(arrs):
    names = [
        "req",
        "ports",
    ]
    xs = {k: getattr(arrs, k) for k in names}
    xs["_pod_index"] = 7
    return xs


def _live_xs_names(cfg):
    live = {"req", "_pod_index"}
    if cfg.enable_ports:
        live.add("ports")
    return live


def _step(weights, state, x):
    used = x["req"] * weights + x["ports"].sum() + x["_pod_index"]
    return state + used.sum(), used


def schedule(arrs, cfg, weights):
    xs = _pod_xs(arrs)
    live = _live_xs_names(cfg)
    xs = {k: v for k, v in xs.items() if k in live}
    step = functools.partial(_step, weights)
    return jax.lax.scan(step, jnp.zeros(()), xs)
