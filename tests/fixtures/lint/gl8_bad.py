"""GL8 fixture (bad): boundary functions that lose the error taxonomy.

  * a `do_*` handler whose broad except swallows the error (the client
    sees nothing classified);
  * a decorator-routed handler — wrapped in a SECOND decorator, which
    must not hide it from boundary detection — doing the same;
  * a handler raising a bare builtin that escapes to the return
    (unclassified 500);
  * a thread worker swallowing everything with `pass`;
  * a `do_*` handler that just dispatches to `self._do_delete()`, whose
    broad except swallows — one delegation level must not hide it.
"""

import functools
import threading
from http.server import BaseHTTPRequestHandler


class FixtureHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        try:
            body = self._answer()
        except Exception:
            body = {"ok": False}   # swallowed: no status mapping
        self._send(200, body)

    def do_POST(self):
        raw = self.rfile.read(16)
        if not raw:
            raise ValueError("empty body")   # escapes: unclassified 500
        self._send(200, {"n": int(raw)})

    def do_DELETE(self):
        self._do_delete()

    def _do_delete(self):
        try:
            self._answer()
        except Exception:
            self._send(500, {"error": "delete failed"})   # unclassified

    def _answer(self):
        return {"ok": True}

    def _send(self, status, payload):
        self.send_response(status)


def observed(fn):
    @functools.wraps(fn)
    def wrap(*a, **kw):
        return fn(*a, **kw)

    return wrap


def route(path):
    def wrap(fn):
        return fn

    return wrap


@observed
@route("/simulate")
def simulate_endpoint(body):
    try:
        return {"result": body["cluster"]}
    except Exception:
        return {"ok": False}   # swallowed at a routed boundary


def _worker(queue):
    while True:
        job = queue.get()
        try:
            job()
        except Exception:
            pass   # the queue worker eats the taxonomy


def start(queue):
    t = threading.Thread(target=_worker, daemon=True)
    t.start()
    return t
