"""GL8 fixture (clean): every boundary answers through the status map.

  * a broad except in a `do_*` handler that maps the error through
    `status_for` / `error_payload` (never a silent swallow);
  * a decorator-routed handler that re-raises as a SimulationError
    subclass (its .code maps through STATUS_BY_CODE upstream);
  * a builtin raise that is fine because a LOCAL try/except catches it
    before the handler returns;
  * a thread worker that classifies via `classify` before logging.

This file must produce ZERO findings under every rule.
"""

import threading
from http.server import BaseHTTPRequestHandler

from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.server.serving import error_payload, status_for


class FixtureBadRequest(SimulationError):
    code = "E_VALIDATION"


class FixtureHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        try:
            body = self._answer()
        except Exception as e:  # mapped, not swallowed
            self._send(status_for(e), error_payload(e))
            return
        self._send(200, body)

    def do_POST(self):
        raw = self.rfile.read(16)
        try:
            if not raw:
                raise ValueError("empty body")  # caught just below
            n = int(raw)
        except ValueError:
            # the builtin never escapes: re-raised as a classified error
            raise FixtureBadRequest("body must be an integer")
        self._send(200, {"n": n})

    def _answer(self):
        return {"ok": True}

    def _send(self, status, payload):
        self.send_response(status)


def route(path):
    def wrap(fn):
        return fn

    return wrap


@route("/simulate")
def simulate_endpoint(body):
    if "cluster" not in body:
        raise FixtureBadRequest("missing cluster")
    return {"ok": True}


def classify(e):
    return "E_BACKEND"


def _worker(queue, log):
    while True:
        job = queue.get()
        try:
            job()
        except Exception as e:  # classified before logging
            log.append(classify(e))


def start(queue, log):
    t = threading.Thread(target=_worker, args=(queue, log), daemon=True)
    t.start()
    return t
