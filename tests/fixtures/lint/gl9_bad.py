"""GL9 fixture (bad): direct durable writes bypassing the fault domain.

Each write below skips DurableJournal/faults.run_io: no torn-tail
framing, no checkpointing_disabled rung when the disk fills, and the
storage fault injector never sees it — exactly the drift GL9 exists to
stop in resilience/, telemetry/, campaign/ and replay/ (this file opts
in via its `gl9_` name prefix).
"""

import json
import os


def dump_state(path, payload):
    with open(path, "w", encoding="utf-8") as f:   # direct "w" open
        json.dump(payload, f)


def append_row(path, line):
    fd = os.open(path, os.O_WRONLY | os.O_APPEND)
    os.write(fd, line.encode())                    # raw os.write
    os.fsync(fd)                                   # raw os.fsync
    os.close(fd)
