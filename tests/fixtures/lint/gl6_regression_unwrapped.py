"""GL6 regression fixture: the PR-14 incident shape.

The serving path jitted a kernel, invoked it, and then called
`block_until_ready()` directly — outside `faults.run_launch` — so a
device loss during the sync surfaced as an unclassified traceback
instead of a structured E_DEVICE_LOST with a retry/degrade rung. Both
the bare jit-result invoke and the naked sync must flag GL6.
"""

import jax
import jax.numpy as jnp


def _kernel(xs):
    return jnp.sum(xs)


def serve_once(xs):
    fn = jax.jit(_kernel)
    out = fn(xs)              # jit result invoked outside the domain
    out.block_until_ready()   # the PR-14 line: naked device sync
    return out
