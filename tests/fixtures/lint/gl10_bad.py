"""GL10 fixture (bad): a drifted metric name.

The family is declared as `simon_fixture_runs_total`; the dashboard
helper greps `simon_fixture_run_total` (dropped `s`). The scrape
silently matches nothing — the exact failure mode GL10 pins.
"""

from open_simulator_tpu.telemetry import counter


def declare():
    return counter("simon_fixture_runs_total", "fixture runs")


def scrape(registry):
    return registry.collect("simon_fixture_run_total")   # drifted name
