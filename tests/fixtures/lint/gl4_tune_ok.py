"""GL4 fixture (clean): the SAFE traced-score-weights pattern
(companion to gl4_waves_ok.py; the tune subsystem's engine shape).

The traced-weights mode (EngineConfig.traced_weights, ARCHITECTURE.md
§17) turns the K score-plugin weights into a traced ``[K]`` input of the
step so W policy variants run as lanes of ONE executable. The sanctioned
shape, which this file pins GL4-clean:

* gate selection is Python control flow on STATIC config — the enable
  flags and the ``traced`` mode flag itself (hashable EngineConfig
  fields baked into the trace), never on a weight value in traced mode;
* the traced weights are only ever SLICED and MULTIPLIED — ``w = wvec[i]``
  then ``score += w * term`` — a zero weight contributes an exact +0.0
  instead of compiling its plugin out, which is what keeps the traced
  path bit-identical to the constant path at the same vector;
* the constant mode may still branch on its (static float) weights —
  that is compile-time dead-code elimination, not a host sync.

Branching on a traced weight (``if wvec[0]:`` inside the trace) is the
GL4 violation this pattern exists to avoid; the negative example lives
in gl4_trace.py.
"""

import jax
import jax.numpy as jnp

WEIGHT_FIELDS = ("w_balanced", "w_least", "w_spread")


def run_step(alloc, req, wvec_host, *, traced, enable_spread,
             w_balanced, w_least, w_spread):
    # static gates: Python bools/floats off the hashable config — in
    # traced mode every enabled row stays live (`traced or weight`),
    # in constant mode a zero weight compiles its row out
    use_bal = bool(traced or w_balanced)
    use_least = bool(traced or w_least)
    use_spread = bool(traced or w_spread) and enable_spread

    @jax.jit
    def step(headroom, req_p, wvec):
        if traced:  # static mode flag, not a traced value
            # traced weights: slice the [K] input; multiply, never branch
            w_bal, w_lst, w_sp = (wvec[i] for i in range(len(WEIGHT_FIELDS)))
        else:
            # constant mode: static floats folded into the trace
            w_bal, w_lst, w_sp = w_balanced, w_least, w_spread
        h = (headroom - req_p) / jnp.maximum(headroom, 1.0)
        score = jnp.zeros(headroom.shape[:1], jnp.float32)
        if use_bal:
            score = score + w_bal * (1.0 - jnp.abs(h[:, 0] - h[:, 1]))
        if use_least:
            score = score + w_lst * jnp.maximum(h, 0.0).sum(axis=1)
        if use_spread:
            score = score + w_sp * (h[:, 0] * 0.5)
        return jnp.argmax(score)

    return step(jnp.asarray(alloc), jnp.asarray(req),
                jnp.asarray(wvec_host, jnp.float32))


def run_lanes(alloc, req, weight_matrix_host, cfg_flags):
    # the tune lane axis: vmap over a [W, K] weight matrix — one
    # executable, W policy variants; weights enter ONLY as traced input
    @jax.jit
    def lanes(headroom, req_p, wmat):
        def lane(wvec):
            score = wvec[0] * headroom[:, 0] + wvec[1] * req_p[0]
            return jnp.argmax(score)

        return jax.vmap(lane)(wmat)

    return lanes(jnp.asarray(alloc), jnp.asarray(req),
                 jnp.asarray(weight_matrix_host, jnp.float32))
