"""GL4 fixture (clean): the SAFE pattern for metric reads near jit scope.

Telemetry is host-side: record from RECORDED outputs — after the
device->host hop (np.asarray / block) OUTSIDE the traced function — and
keep the traced body pure jnp. This file must produce ZERO findings; it
is the positive example the telemetry instrumentation across core.py /
simulator.py / sweep.py follows (the negative example — .item() on a
traced value inside the jit — lives in gl4_trace.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from open_simulator_tpu.telemetry import counter, histogram


@functools.partial(jax.jit, static_argnames=("cfg",))
def traced_step(cfg, xs):
    # traced scope: pure jnp math, no host sync, no metric calls
    scale = 2.0 if cfg else 1.0  # static flag: host branch is fine
    return jnp.sum(xs) * scale


def run_and_record(values):
    out = traced_step(True, jnp.asarray(values))
    hosted = float(np.asarray(out))  # device -> host OUTSIDE the jit
    histogram("fixture_run_seconds").observe(hosted)
    counter("fixture_runs_total").inc()
    return hosted
