"""GL4 fixture (clean): the SAFE pattern for the host-side wave
partitioner next to jit scope (companion to gl4_execcache_ok.py).

The wave scheduler (engine/waves.py) runs its whole conflict analysis on
HOST numpy BEFORE the jit boundary: footprints, channel sets, and the
greedy wave accumulation are Python/numpy control flow over encoded host
arrays, and the resulting plan enters the traced engine only as a STATIC
argument (tuples of Python ints — segment bounds and kinds). Inside the
trace, Python loops iterate over those static segment tuples (gate
selection, not a host sync), and the traced math per segment stays pure
jnp. This file must produce ZERO findings; the negative example
(branching on a traced value inside jit) lives in gl4_trace.py.
"""

import numpy as np

import jax
import jax.numpy as jnp


def plan_waves(req_host, footprint_host):
    # HOST analysis on HOST numpy (the encode output, pre-transfer):
    # greedy contiguous partition into runs whose footprints are disjoint
    segments = []
    start = 0
    written = np.zeros(footprint_host.shape[1], dtype=bool)
    for i in range(req_host.shape[0]):
        if bool(np.any(footprint_host[i] & written)):  # host bool: safe
            segments.append((start, i))
            start = i
            written[:] = False
        written |= footprint_host[i]
    segments.append((start, req_host.shape[0]))
    return tuple(segments)  # static plan: Python ints only


def run_planned(req_host, footprint_host, alloc):
    segments = plan_waves(np.asarray(req_host), np.asarray(footprint_host))

    @jax.jit
    def exec_plan(req, headroom):
        # Python loop over STATIC segment bounds (host ints baked into
        # the trace — segment selection, not a traced-value branch)
        for lo, hi in segments:
            if hi - lo > 1:  # static width: batch the independent run
                headroom = headroom - jnp.sum(req[lo:hi], axis=0)
            else:
                headroom = headroom - req[lo]
        return headroom

    return exec_plan(jnp.asarray(req_host), jnp.asarray(alloc))
