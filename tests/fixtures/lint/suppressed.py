"""Suppression fixture: justified vs unjustified disables.

The first host sync carries a justified suppression (no finding); the
second suppresses GL4 without a reason — the GL4 finding is swallowed
but GL0 flags the naked directive.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
import jax
import jax.numpy as jnp


@jax.jit
def summarize(a):
    # graftlint: disable=GL4 debug helper: the host read is the point
    total = float(jnp.sum(a))
    bad = int(jnp.max(a))  # graftlint: disable=GL4
    return total + bad
