"""GL4 fixture (clean): the SAFE pattern for host-side run-ledger writes
next to jit scope (companion to gl4_telemetry_ok.py / gl4_execcache_ok.py).

The flight recorder (telemetry/ledger.py) appends one JSON line per run:
result digests hash DECODED outputs (`np.asarray` after the device
blocked), fingerprints hash static config/shape metadata, and the file
append plus counter-delta bookkeeping are plain host I/O on host values.
None of it runs inside the trace; the traced body stays pure jnp. This
file must produce ZERO findings — the negative example (hashing or
branching on a traced value inside jit) lives in gl4_trace.py.
"""

import hashlib
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.telemetry import counter


def _traced_assign(req, cap):
    # traced scope: pure jnp math — no hashing, no file writes, no host
    # branches on traced values
    fits = req[:, None] <= cap[None, :]
    return jnp.argmax(fits, axis=1) - (1 - jnp.max(fits, axis=1))


def run_and_record(requests, capacities, ledger_path, surface="fixture"):
    req = jnp.asarray(requests)
    cap = jnp.asarray(capacities)
    # fingerprint from STATIC metadata (shapes/dtypes are host values even
    # on traced arrays; reading them never syncs the device)
    fingerprint = hashlib.sha256(
        repr((tuple(req.shape), str(req.dtype), tuple(cap.shape))).encode()
    ).hexdigest()[:16]
    t0 = time.perf_counter()
    out = faults.run_launch("fixture_assign",
                            lambda: jax.jit(_traced_assign)(req, cap))
    assign = np.asarray(out)  # device -> host OUTSIDE the jit, blocks
    wall = time.perf_counter() - t0  # host timing around the call, host-side
    digest = hashlib.sha256(np.ascontiguousarray(assign).tobytes()).hexdigest()[:16]
    record = {
        "surface": surface,
        "fingerprint": fingerprint,
        "digest": digest,
        "placed": int(np.sum(assign >= 0)),  # host reduction on hosted array
        "wall_s": round(wall, 6),
    }
    counter("fixture_ledger_records_total",
            labelnames=("surface",)).labels(surface=surface).inc()
    with open(ledger_path, "a", encoding="utf-8") as f:  # host file append
        f.write(json.dumps(record, sort_keys=True) + "\n")
    return record
