"""GL3 fixture: a config class with one dead field and one dead property.

Never executed — parsed by graftlint only (tests/test_graftlint.py).
"""
from typing import NamedTuple


class EngineConfig(NamedTuple):
    n_resources: int
    enable_gpu: bool = False
    stale_knob: bool = True  # GL3: set by nobody's reader

    @property
    def doubled(self) -> int:
        # alive: read by consume() below; keeps n_resources alive too
        return self.n_resources * 2

    @property
    def unused_prop(self) -> bool:  # GL3: never referenced anywhere
        return self.enable_gpu


def consume(cfg: EngineConfig) -> int:
    if cfg.enable_gpu:
        return cfg.doubled
    return 0
