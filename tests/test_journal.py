"""Durable-state fault domain tests (resilience/journal.py, ISSUE 16).

Covers the framed-journal integrity contract across all four journal
kinds: the ~50-seed mutation fuzz (bit-flips, truncations, duplicated
lines, reordered sequence numbers -> either a bit-identical prefix
resume or a structured E_CORRUPT, never a traceback and never a
wrong-prefix resume), strict torn-tail-only recovery, legacy unframed
compatibility, the storage fault taxonomy (ENOSPC deterministic / EIO
transient) with the shared checkpointing_disabled degradation rung, the
ENOSPC-mid-run regression (the run finishes, the report says so, resume
from the surviving prefix is digest-identical), SessionStore startup
quarantine, and the ledger's skipped_corrupt surfacing."""

import errno
import json
import os
import random

import pytest

from open_simulator_tpu import telemetry
from open_simulator_tpu.errors import SimulationError
from open_simulator_tpu.resilience import faults, lifecycle
from open_simulator_tpu.resilience import journal as journal_mod
from open_simulator_tpu.resilience.journal import (
    DurableJournal,
    JournalCorrupt,
    frame_record,
    read_journal,
    scan_integrity,
    unframe_line,
)

# ---- builders: one real journal per kind ---------------------------------


def _build_sweep(root):
    j = lifecycle.SweepJournal.create(
        str(root), {"engine": "x", "cfg": 1}, 4, 2, (100.0, 99.5, 99.0))
    for r in range(3):
        j.append_round([r + 1], {r + 1: {"nodes": [r], "error": None}})
    j.finish(3, "digest-sweep")
    return j.path, lambda: lifecycle.SweepJournal.load(str(root), "last")


def _build_campaign(root):
    from open_simulator_tpu.campaign.runner import CampaignJournal

    j = CampaignJournal.create(str(root), "fleetdig", "scale", 3)
    for i in range(3):
        j.append_cluster(f"c{i}", {"source": f"s{i}"},
                         {"cluster": f"c{i}", "ok": True})
    j.finish("digest-campaign", 3, 0)
    return j.path, lambda: CampaignJournal.load(str(root), "last")


def _build_replay(root):
    from open_simulator_tpu.replay.engine import ReplayJournal

    j = ReplayJournal.create(str(root), {"trace": "t"}, 3,
                             [{"kind": "autoscaler"}])
    for i in range(3):
        j.append_step({"t": i, "event": {"kind": "arrival"}, "placed": i})
    j.finish("digest-replay", 3)
    return j.path, lambda: ReplayJournal.load(str(root), "last")


def _build_session(root):
    from open_simulator_tpu.replay.session import SessionJournal, SessionSpec

    j = SessionJournal.create(str(root), "sid0fuzz", "fuzz", {"f": 1},
                              [{"kind": "Node"}], SessionSpec(), [])
    for i in range(3):
        j.append_step({"t": i, "kind": "arrival"}, {"t": i, "placed": i})
    j.close("digest-session", 3)
    return j.path, lambda: SessionJournal.load(j.path)


_BUILDERS = {
    "sweep": _build_sweep,
    "campaign": _build_campaign,
    "replay": _build_replay,
    "session": _build_session,
}


# ---- the ~50-seed mutation fuzz (satellite 1) ----------------------------


def _mutate_journal(data: bytes, rng: random.Random) -> bytes:
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    op = rng.choice(["bit_flip", "truncate", "dup_line", "swap_lines",
                     "drop_line", "garbage_tail", "blank_line"])
    if op == "bit_flip":
        buf = bytearray(data)
        i = rng.randrange(len(buf))
        buf[i] ^= 1 << rng.randrange(8)
        return bytes(buf)
    if op == "truncate":
        return data[: rng.randrange(1, len(data))]
    if op == "dup_line":
        i = rng.randrange(len(lines))
        lines.insert(i, lines[i])
    elif op == "swap_lines":
        i = rng.randrange(len(lines) - 1)
        lines[i], lines[i + 1] = lines[i + 1], lines[i]
    elif op == "drop_line":
        del lines[rng.randrange(len(lines))]
    elif op == "garbage_tail":
        lines.append(bytes(rng.randrange(256) for _ in range(20)))
    elif op == "blank_line":
        lines.insert(rng.randrange(len(lines) + 1), b"")
    return b"\n".join(lines) + b"\n"


@pytest.mark.parametrize("seed", range(50))
def test_journal_mutation_fuzz(tmp_path, seed):
    """The strict-reader contract under 50 seeded mutations, cycling
    through all four journal kinds: every read either returns an EXACT
    prefix of the pristine records (the only damage a torn tail may
    cost) or raises a structured E_CORRUPT naming kind/index/offset —
    never a traceback, never a resumed wrong prefix."""
    kind = list(_BUILDERS)[seed % len(_BUILDERS)]
    rng = random.Random(seed)
    path, load = _BUILDERS[kind](tmp_path)
    truth = read_journal(path, kind).records
    assert len(truth) == 5  # header + 3 + done

    data = open(path, "rb").read()
    mutated = _mutate_journal(data, rng)
    with open(path, "wb") as f:
        f.write(mutated)
    if mutated == data:
        return  # the mutation was a no-op for this seed

    try:
        scan = read_journal(path, kind)
    except JournalCorrupt as e:
        assert e.code == "E_CORRUPT"
        assert e.kind == kind and e.index >= 0 and e.offset >= 0
        d = e.to_dict()
        assert d["journal"]["kind"] == kind
        # the kind-specific load path must agree (same strict reader)
        with pytest.raises(JournalCorrupt):
            load()
        return
    # accepted: the surviving records must be an exact, bit-identical
    # prefix of the pristine history — NEVER a subsequence with a hole
    assert scan.records == truth[: len(scan.records)], (kind, seed)


# ---- torn tail: the one forgiven damage ----------------------------------


@pytest.mark.parametrize("kind", sorted(_BUILDERS))
def test_torn_tail_resumes_from_prefix_and_heals(tmp_path, kind):
    path, load = _BUILDERS[kind](tmp_path)
    truth = read_journal(path, kind).records
    with open(path, "ab") as f:
        f.write(b'J1 deadbeef 5 {"kind": "torn')  # partial final write

    scan = read_journal(path, kind)
    assert scan.torn_tail and scan.records == truth
    assert scan.integrity() == {"format": "framed", "torn_tail": True}
    j = load()  # the kind-specific load tolerates it too
    assert j.torn_tail

    # resuming appends must first DROP the partial bytes: appending
    # after them would turn the forgiven tail into mid-file corruption
    j._append({"kind": "extra", "n": 1})
    healed = read_journal(path, kind)
    assert not healed.torn_tail
    assert healed.records == truth + [{"kind": "extra", "n": 1}]


def test_mid_file_corruption_is_structured(tmp_path):
    """A flipped byte anywhere but the final line is E_CORRUPT with the
    kind, record index, and byte offset of the damage."""
    path, load = _BUILDERS["sweep"](tmp_path)
    lines = open(path, "rb").read().split(b"\n")
    buf = bytearray(lines[1])
    buf[len(buf) // 2] ^= 0x10
    lines[1] = bytes(buf)
    with open(path, "wb") as f:
        f.write(b"\n".join(lines))
    with pytest.raises(JournalCorrupt) as ei:
        load()
    e = ei.value
    assert e.code == "E_CORRUPT" and e.kind == "sweep" and e.index == 1
    assert e.offset == len(lines[0]) + 1
    assert "crc mismatch" in e.message
    verdict = scan_integrity(path, "sweep")
    assert verdict is not None and verdict.index == 1


def test_sequence_gap_and_duplicate_are_corrupt(tmp_path):
    """Intact lines at the wrong position keep their CRC but break
    monotonicity: a dropped or duplicated mid-file record can never be a
    torn write, so both are refused."""
    path, _ = _BUILDERS["replay"](tmp_path)
    pristine = open(path, "rb").read().split(b"\n")

    with open(path, "wb") as f:  # drop record #2: a gap
        f.write(b"\n".join(pristine[:2] + pristine[3:]))
    with pytest.raises(JournalCorrupt) as ei:
        read_journal(path, "replay")
    assert "sequence break" in ei.value.message and ei.value.index == 2

    with open(path, "wb") as f:  # duplicate record #1
        f.write(b"\n".join(pristine[:2] + pristine[1:]))
    with pytest.raises(JournalCorrupt):
        read_journal(path, "replay")


def test_legacy_unframed_journal_still_loads(tmp_path):
    """Journals written before the frame format stay readable, are
    flagged legacy, and keep their format on append (mixing framed lines
    into an unframed file would make BOTH readers reject it)."""
    recs = [{"kind": "header", "sweep_id": "legacy01", "fingerprint": {},
             "max_new": 4, "lanes": 2, "thresholds": [100.0]},
            {"kind": "round", "round": 1, "counts": [1],
             "lanes": {"1": {"nodes": [0]}}}]
    path = tmp_path / ("legacy01" + lifecycle.SWEEP_JOURNAL_SUFFIX)
    with open(path, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")

    j = lifecycle.SweepJournal.load(str(tmp_path), "legacy01")
    assert j.legacy and len(j.rounds) == 1
    assert j.integrity()["format"] == "legacy"
    j.append_round([2], {2: {"nodes": [0]}})
    raw = open(path, "rb").read()
    assert not any(ln.startswith(b"J1 ")
                   for ln in raw.split(b"\n") if ln)
    again = lifecycle.SweepJournal.load(str(tmp_path), "legacy01")
    assert len(again.rounds) == 2 and again.legacy


def test_unframe_line_round_trip(tmp_path):
    framed = frame_record(7, {"kind": "x", "v": 1}).decode()
    assert json.loads(unframe_line(framed)) == {"kind": "x", "v": 1}
    legacy = json.dumps({"kind": "y"}) + "\n"
    assert json.loads(unframe_line(legacy)) == {"kind": "y"}


# ---- storage fault taxonomy (the PR-14 discipline for disks) -------------


def test_classify_storage_errnos():
    full = faults.classify(OSError(errno.ENOSPC, "No space left on device"))
    assert full.code == "E_STORAGE_FULL" and not full.transient
    assert faults.classify(
        OSError(errno.EDQUOT, "Disk quota exceeded")).code == "E_STORAGE_FULL"
    assert faults.classify(
        OSError(errno.EROFS, "Read-only file system")).code == "E_STORAGE_FULL"
    eio = faults.classify(OSError(errno.EIO, "Input/output error"))
    assert eio.code == "E_STORAGE_IO" and eio.transient
    # message-only classification (a wrapped OSError without errno)
    assert faults.classify(
        OSError("No space left on device")).code == "E_STORAGE_FULL"
    # a bare OSError stays in the transfer bucket (transient, retried)
    assert faults.classify(OSError("weird")).code == "E_TRANSFER"


def test_enospc_on_append_takes_disable_rung(tmp_path, caplog):
    """A full disk mid-run latches the shared checkpointing_disabled
    rung ONCE: counted per kind+code, ledger-evented, warn-once — and
    the surviving prefix stays loadable."""
    disabled = telemetry.counter("simon_journal_disabled_total",
                                 labelnames=("kind", "code"))
    rungs = telemetry.counter("simon_fault_rungs_total",
                              labelnames=("fn", "rung"))
    b_dis = disabled.value(kind="sweep", code="E_STORAGE_FULL")
    b_rung = rungs.value(fn="journal_append", rung="checkpointing_disabled")

    j = lifecycle.SweepJournal.create(str(tmp_path), {"f": 1}, 4, 2, (100.0,))
    with faults.injected("fn=journal_append,exc=ENOSPC,launch=0,times=9"):
        j.append_round([1], {1: {"nodes": [0]}})   # hits the full disk
        j.append_round([2], {2: {"nodes": [0]}})   # silently skipped
        j.finish(2, "d")
    assert j.broken and j.broken_code == "E_STORAGE_FULL"
    assert j.integrity()["checkpointing_disabled"] is True
    assert j.integrity()["storage_fault"] == "E_STORAGE_FULL"
    assert disabled.value(kind="sweep", code="E_STORAGE_FULL") == b_dis + 1
    assert rungs.value(fn="journal_append",
                       rung="checkpointing_disabled") == b_rung + 1
    # the prefix on disk is intact: header only (round 1 never landed)
    scan = read_journal(j.path, "sweep")
    assert [r["kind"] for r in scan.records] == ["header"]


def test_eio_is_transient_and_retried(tmp_path):
    """One EIO is absorbed by the run_io retry schedule: the append
    lands on the retry and journaling stays enabled."""
    j = lifecycle.SweepJournal.create(str(tmp_path), {"f": 1}, 4, 2, (100.0,))
    with faults.injected("fn=journal_append,exc=eio,launch=0,times=1"):
        j.append_round([1], {1: {"nodes": [0]}})
        stats = faults.injection_stats()
    assert not j.broken
    assert stats["injected"]["journal_append"] == 1
    assert stats["launches"]["journal_append"] == 2  # the EIO + the retry
    scan = read_journal(j.path, "sweep")
    assert [r["kind"] for r in scan.records] == ["header", "round"]
    assert not scan.torn_tail  # the failed attempt left no partial line


def test_storage_plan_round_trips_and_counts_match():
    """Satellite 4: the I/O-site grammar round-trips through canonical()
    and the injected counters match the plan exactly."""
    plan = faults.FaultPlan.parse("fn=journal_append,exc=ENOSPC,launch=2;"
                                  "fn=ledger_append,exc=eio")
    assert plan.canonical() == ("fn=journal_append,exc=enospc,launch=2,"
                                "times=1;fn=ledger_append,exc=eio,launch=0,"
                                "times=1")
    assert faults.FaultPlan.parse(plan.canonical()) == plan

    with faults.injected("fn=journal_append,exc=enospc,launch=1,times=2"):
        for _ in range(4):
            try:
                faults.run_io("journal_append", lambda: None, backoff_s=0.0)
            except faults.DeviceFault as e:
                assert e.code == "E_STORAGE_FULL"
        stats = faults.injection_stats()
    assert stats["injected"] == {"journal_append": 2}
    assert stats["launches"] == {"journal_append": 4}


@pytest.mark.parametrize("text,field", [
    ("fn=journal_append,exc=enospc,launch=-1", "rules[0].launch"),
    ("fn=journal_append,exc=ENOSPC!", "rules[0].exc"),
    ("fn=journal_append", "rules[0].exc"),
    ("fn=ledger_append,exc=eio,times=0", "rules[0].times"),
    ("fn=journal_rotate,exc=enospc", "rules[0].fn"),
])
def test_malformed_storage_rules_are_e_spec(text, field):
    with pytest.raises(SimulationError) as ei:
        faults.FaultPlan.parse(text)
    assert ei.value.code == "E_SPEC" and ei.value.field == field


# ---- the ENOSPC-mid-run regression (satellite 2) -------------------------


def _bisect_fixture():
    from tests.test_lifecycle import _snapshot
    from open_simulator_tpu.engine.scheduler import make_config

    snap = _snapshot()
    return snap, make_config(snap)


def test_enospc_mid_sweep_finishes_and_resumes_identically(
        tmp_path, monkeypatch):
    """The disk fills on round-2's append: the sweep FINISHES with the
    rung counted and the plan saying so, and resuming from the journal's
    surviving prefix is digest-identical to the uninterrupted run."""
    from open_simulator_tpu.parallel.sweep import capacity_bisect
    from open_simulator_tpu.telemetry.ledger import plan_digest

    monkeypatch.setenv(lifecycle.CHECKPOINT_DIR_ENV, str(tmp_path))
    snap, cfg = _bisect_fixture()
    reference = capacity_bisect(snap, cfg, 8, lanes=2)
    assert not reference.checkpointing_disabled
    for n in os.listdir(tmp_path):
        os.unlink(tmp_path / n)

    # header is append #0; round 1 lands; round 2's append hits ENOSPC
    with faults.injected("fn=journal_append,exc=enospc,launch=2,times=99"):
        degraded = capacity_bisect(snap, cfg, 8, lanes=2)
    assert degraded.checkpointing_disabled
    assert degraded.best_count == reference.best_count
    assert plan_digest(degraded)["digest"] == plan_digest(reference)["digest"]

    # the surviving prefix (header + round 1) resumes digest-identically
    [name] = [n for n in os.listdir(tmp_path)
              if n.endswith(lifecycle.SWEEP_JOURNAL_SUFFIX)]
    j = lifecycle.SweepJournal.load(str(tmp_path), "last")
    assert [r["round"] for r in j.rounds] == [1] and j.done is None
    resumed = capacity_bisect(snap, cfg, 8, lanes=2,
                              resume=name.split(".")[0])
    assert resumed.resumed_rounds == 1
    assert not resumed.checkpointing_disabled
    assert plan_digest(resumed)["digest"] == plan_digest(reference)["digest"]


# ---- SessionStore startup quarantine -------------------------------------


def test_session_store_quarantines_corrupt_journal(tmp_path):
    """A mid-file-corrupt session journal is quarantined at scan: the
    store boots, siblings rehydrate, the corrupt sid reports its stored
    E_CORRUPT on touch and shows up flagged in list()."""
    from open_simulator_tpu.replay import (
        ReplaySession,
        SessionSpec,
        SessionStore,
        synthetic_replay_cluster,
        synthetic_trace_dict,
    )

    td = synthetic_trace_dict(n_batches=2, batch_pods=2,
                              max_new_nodes=2)
    cluster = synthetic_replay_cluster(n_nodes=2, n_initial_pods=2)
    spec = SessionSpec(max_new_nodes=2, node_template=td["node_template"])
    sess = ReplaySession.create(cluster, spec, controllers=[],
                                root=str(tmp_path))
    sess.apply_events(td["events"][:1])
    sid_ok = sess.session_id

    corrupt_path, _ = _BUILDERS["session"](tmp_path)
    lines = open(corrupt_path, "rb").read().split(b"\n")
    lines[1] = lines[1][:-4] + b"XXXX"  # mid-file CRC break
    # drop the close record so the journal counts as an OPEN session
    with open(corrupt_path, "wb") as f:
        f.write(b"\n".join(lines[:-2]) + b"\n")

    store = SessionStore(root=str(tmp_path))
    found = store.scan()
    assert sid_ok in found and "sid0fuzz" not in found
    assert "sid0fuzz" in store.quarantined()

    with pytest.raises(JournalCorrupt) as ei:
        store.get("sid0fuzz")
    assert ei.value.code == "E_CORRUPT"
    from open_simulator_tpu.server.serving import STATUS_BY_CODE
    assert STATUS_BY_CODE["E_CORRUPT"] == 409

    rows = store.list()
    flagged = [r for r in rows if r.get("corrupt")]
    assert [r["session_id"] for r in flagged] == ["sid0fuzz"]
    assert flagged[0]["error"]["code"] == "E_CORRUPT"
    # the sibling is untouched by the quarantine
    ok = store.get(sid_ok)
    assert ok.session_id == sid_ok


# ---- ledger skipped_corrupt (satellite 3) --------------------------------


def test_ledger_counts_and_surfaces_skipped_corrupt(tmp_path, capsys,
                                                    monkeypatch):
    from open_simulator_tpu.telemetry import ledger as ledger_mod
    from open_simulator_tpu.telemetry.ledger import Ledger

    led = Ledger(str(tmp_path))
    for i in range(3):
        led.append({"run_id": f"r{i}", "surface": "bench", "ts": i})
    lines = open(led.path, encoding="utf-8").read().splitlines()
    lines.insert(1, '{"torn half rec')      # undecodable
    lines.insert(3, '["not", "a", "dict"]')  # decodable, not a record
    lines.insert(4, '')                      # blank: ignored, not counted
    with open(led.path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")

    led2 = Ledger(str(tmp_path))
    recs = led2.records()
    assert [r["run_id"] for r in recs] == ["r0", "r1", "r2"]
    assert led2.skipped_corrupt == 2

    # the REST index carries the count
    from open_simulator_tpu.server import rest as rest_mod
    ledger_mod.configure(str(tmp_path))
    try:
        srv = rest_mod.SimulationServer()
        out = srv.runs_index({})
        assert out["skipped_corrupt"] == 2 and len(out["runs"]) == 3
    finally:
        ledger_mod.configure(None)

    # and the CLI warns on the runs surfaces
    from open_simulator_tpu.cli.main import main as cli_main
    try:
        rc = cli_main(["runs", "--ledger-dir", str(tmp_path), "list"])
    finally:
        ledger_mod.configure(None)
    assert rc == 0
    err = capsys.readouterr().err
    assert "skipped 2 corrupt ledger record(s)" in err


def test_bench_regress_warns_on_corrupt_window(tmp_path, capsys):
    from open_simulator_tpu.telemetry import ledger as ledger_mod
    from open_simulator_tpu.telemetry.ledger import Ledger
    from tools.bench_regress import main as bench_main

    led = Ledger(str(tmp_path))
    for i in range(2):
        led.append({"run_id": f"b{i}", "surface": "bench", "ts": i,
                    "metrics": {"wall_s": 1.0}})
    with open(led.path, "a", encoding="utf-8") as f:
        f.write('{"torn\n')
    try:
        rc = bench_main(["--ledger-dir", str(tmp_path)])
    finally:
        ledger_mod.configure(None)
    err = capsys.readouterr().err
    assert "skipped 1 corrupt ledger record(s)" in err
    assert rc == 0  # nothing gate-able in the window is not a failure


# ---- resolve_journal_path (the shared token resolution) ------------------


def test_resolve_journal_path_errors(tmp_path):
    with pytest.raises(lifecycle.ResumeError):
        journal_mod.resolve_journal_path(
            str(tmp_path / "absent"), "last", ".sweep.jsonl", "sweep")
    with pytest.raises(lifecycle.ResumeError):
        journal_mod.resolve_journal_path(
            str(tmp_path), "last", ".sweep.jsonl", "sweep")
    (tmp_path / "aaa111.sweep.jsonl").write_text("")
    (tmp_path / "aaa222.sweep.jsonl").write_text("")
    with pytest.raises(lifecycle.ResumeError) as ei:
        journal_mod.resolve_journal_path(
            str(tmp_path), "aaa", ".sweep.jsonl", "sweep")
    assert "ambiguous" in ei.value.message
    got = journal_mod.resolve_journal_path(
        str(tmp_path), "aaa1", ".sweep.jsonl", "sweep")
    assert got.endswith("aaa111.sweep.jsonl")


def test_empty_journal_is_not_torn(tmp_path):
    p = tmp_path / "empty.sweep.jsonl"
    p.write_text("")
    scan = read_journal(str(p), "sweep")
    assert scan.records == [] and not scan.torn_tail and not scan.legacy
