"""Interactive apply mode + public fixture builders."""

import io
import os

from open_simulator_tpu.cli.main import main
from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from open_simulator_tpu.testing import (
    make_fake_daemonset,
    make_fake_deployment,
    make_fake_job,
    make_fake_node,
    make_fake_pod,
    make_fake_statefulset,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_interactive_flow(monkeypatch, capsys):
    # select all apps, then quit is never needed (everything fits)
    answers = iter(["", ""])
    monkeypatch.setattr("builtins.input", lambda *a: next(answers))
    rc = main(["apply", "-f", os.path.join(REPO, "examples/config.yaml"),
               "--max-new-nodes", "2", "-i"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "select apps to deploy" in out
    assert "all pods scheduled with 0 new node(s)" in out


def test_interactive_add_nodes(monkeypatch, capsys, tmp_path):
    import textwrap

    (tmp_path / "cluster").mkdir()
    (tmp_path / "cluster" / "n.yaml").write_text(textwrap.dedent("""
        kind: Node
        metadata: {name: small}
        status: {allocatable: {cpu: "1", memory: 2Gi, pods: "110"}}
    """))
    (tmp_path / "apps").mkdir()
    (tmp_path / "apps" / "a.yaml").write_text(textwrap.dedent("""
        kind: Pod
        metadata: {name: fat, namespace: default}
        spec:
          containers:
            - name: c
              resources: {requests: {cpu: "2"}}
    """))
    (tmp_path / "newnode.yaml").write_text(textwrap.dedent("""
        kind: Node
        metadata: {name: tpl}
        status: {allocatable: {cpu: "4", memory: 8Gi, pods: "110"}}
    """))
    (tmp_path / "cfg.yaml").write_text(textwrap.dedent("""
        apiVersion: simon/v1alpha1
        kind: Config
        metadata: {name: t}
        spec:
          cluster: {customConfig: cluster}
          appList: [{name: a, path: apps}]
          newNode: newnode.yaml
    """))
    answers = iter(["", "r", "a 1", ""])
    monkeypatch.setattr("builtins.input", lambda *a: next(answers))
    rc = main(["apply", "-f", str(tmp_path / "cfg.yaml"), "--max-new-nodes", "4", "-i"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 pod(s) unschedulable with 0 new node(s)" in out
    assert "Insufficient cpu" in out           # from [r]easons
    assert "all pods scheduled with 1 new node(s)" in out


def test_builders_end_to_end():
    cluster = ClusterResources()
    cluster.nodes = [make_fake_node(f"n{i}", cpu="8", memory="16Gi") for i in range(3)]
    app = ClusterResources()
    app.deployments = [make_fake_deployment("web", replicas=4, cpu="500m")]
    app.stateful_sets = [make_fake_statefulset("db", replicas=2, cpu="1")]
    app.daemon_sets = [make_fake_daemonset("agent")]
    app.jobs = [make_fake_job("batch", completions=2)]
    app.pods = [make_fake_pod("one-off")]
    res = simulate(cluster, [AppResource(name="t", resources=app)])
    assert not res.unscheduled_pods
    assert len(res.scheduled_pods) == 4 + 2 + 3 + 2 + 1
