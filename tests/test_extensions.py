"""Out-of-tree extension ops (engine/extensions.ExtensionOp) — the
WithFrameworkOutOfTreeRegistry analog (pkg/simulator/simulator.go:188-195),
plus the KubeSchedulerConfiguration filter-disable -> feature-gate mapping
(VERDICT r3 #5/#6).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.engine.extensions import ExtensionOp
from open_simulator_tpu.k8s.loader import ClusterResources
from tests.conftest import make_node, make_pod


def _cluster(n_nodes=4):
    cluster = ClusterResources()
    cluster.nodes = [make_node(f"n{i}") for i in range(n_nodes)]
    return cluster


def _app(pods):
    app = ClusterResources()
    app.pods = pods
    return app


# Worked example 1: a FILTER extension — only even-indexed nodes may host
# the workload (a stand-in for a real policy like "licensed nodes only").
# The mask reads the same inputs built-in ops do: snapshot arrays + carry.
even_nodes_only = ExtensionOp(
    name="node(s) rejected by the even-index policy",
    filter_fn=lambda state, arrs, x: (
        jnp.arange(arrs.alloc.shape[0]) % 2 == 0),
)

# Worked example 2: a SCORE extension — prefer high-index nodes (a
# stand-in for e.g. "prefer newest hardware"), framework-normalized and
# weighted far above the built-in scores.
prefer_last_node = ExtensionOp(
    name="prefer-last-node",
    score_fn=lambda state, arrs, x: jnp.arange(
        arrs.alloc.shape[0], dtype=jnp.float32),
    normalize="minmax",
    weight=1000.0,
)


def test_filter_extension_masks_nodes_and_reports_reason():
    res = simulate(
        _cluster(), [AppResource(name="a", resources=_app(
            [make_pod(f"p{i}", cpu="100m") for i in range(8)]))],
        config_overrides={"extensions": (even_nodes_only,)},
    )
    placed_nodes = set(res.placements().values())
    assert placed_nodes <= {"n0", "n2"}
    # reason surfaces when nothing else fits: make the even nodes full
    res2 = simulate(
        _cluster(2), [AppResource(name="a", resources=_app(
            [make_pod("big0", cpu="3900m"), make_pod("big1", cpu="3900m")]))],
        config_overrides={"extensions": (even_nodes_only,)},
    )
    assert len(res2.unscheduled_pods) == 1
    reason = res2.unscheduled_pods[0].reason
    assert "1 node(s) rejected by the even-index policy" in reason
    assert "1 Insufficient cpu" in reason


def test_score_extension_changes_ranking():
    # identical empty nodes: the deterministic tie-break sends the first
    # pod to n0; the heavily-weighted extension flips the ranking to n3
    pods = [make_pod("p0", cpu="10m", mem="1Mi")]
    base = simulate(_cluster(), [AppResource(name="a", resources=_app(pods))])
    ext = simulate(
        _cluster(), [AppResource(name="a", resources=_app(pods))],
        config_overrides={"extensions": (prefer_last_node,)},
    )
    assert base.placements() == {"default/p0": "n0"}
    assert ext.placements() == {"default/p0": "n3"}


def test_extension_validation():
    with pytest.raises(ValueError):
        ExtensionOp(name="bad", score_fn=lambda *a: 0, normalize="zscore").validate()
    with pytest.raises(ValueError):
        ExtensionOp(name="empty").validate()


def test_profile_filter_disable_maps_to_gates(tmp_path):
    """A KubeSchedulerConfiguration that disables filter plugins turns the
    matching engine gates off (the vendored framework would skip the
    de-registered plugin the same way)."""
    from open_simulator_tpu.engine.sched_config import weight_overrides_from_file

    cfg_file = tmp_path / "sched.yaml"
    cfg_file.write_text("""
apiVersion: kubescheduler.config.k8s.io/v1beta2
kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      filter:
        disabled:
          - name: NodePorts
          - name: InterPodAffinity
          - name: NodeResourcesFit
      postFilter:
        disabled:
          - name: DefaultPreemption
""")
    ov = weight_overrides_from_file(str(cfg_file))
    assert ov["enable_ports"] is False
    assert ov["enable_pod_affinity"] is False and ov["enable_anti_affinity"] is False
    assert ov["_disable_preemption"] is True
    assert "enable_unsched" not in ov  # untouched plugins keep autodetect
    # NodeResourcesFit has no gate: ignored (warned), not crashed
    assert not any(k.startswith("enable_fit") for k in ov)


def test_disabled_taint_filter_schedules_onto_tainted_node():
    """End to end: disabling TaintToleration via the profile gate lets a
    toleration-less pod land on a tainted node."""
    cluster = _cluster(1)
    cluster.nodes[0] = make_node(
        "n0", taints=[{"key": "dedicated", "value": "x", "effect": "NoSchedule"}])
    app = _app([make_pod("p0", cpu="100m")])
    blocked = simulate(cluster, [AppResource(name="a", resources=app)])
    assert len(blocked.unscheduled_pods) == 1
    allowed = simulate(
        cluster, [AppResource(name="a", resources=app)],
        config_overrides={"enable_class_taint": False},
    )
    assert allowed.placements() == {"default/p0": "n0"}
