"""Compile-amortization layer: bucketing, padding equivalence, the AOT
executable LRU, donated carries, and the bisection sweep's parity with
the exhaustive sweep (ISSUE 4 acceptance criteria)."""

import dataclasses
import os

import numpy as np
import pytest

from open_simulator_tpu.core import AppResource, build_pod_sequence, simulate
from open_simulator_tpu.encode.snapshot import (
    NODE_AXIS_FIRST,
    NODE_AXIS_SECOND,
    POD_AXIS_FIRST,
    EncodeOptions,
    SnapshotArrays,
    encode_cluster,
)
from open_simulator_tpu.engine import exec_cache
from open_simulator_tpu.engine.exec_cache import (
    BucketPolicy,
    ExecutableCache,
    bucket_dim,
    bucket_shape,
    pad_snapshot_arrays,
    pad_vector,
    run_batched_cached,
)
from open_simulator_tpu.engine.scheduler import _pod_xs, make_config
from open_simulator_tpu.k8s.loader import ClusterResources, make_valid_node
from open_simulator_tpu.parallel.sweep import (
    active_masks_for_counts,
    capacity_bisect,
    capacity_sweep,
)
from tests.conftest import make_node, make_pod


def _counter(name, **labels):
    from open_simulator_tpu.telemetry import counter

    return counter(name, "", labelnames=tuple(labels)).value(**labels)


def _cluster(n_nodes, n_pods, cpu="500m"):
    cluster = ClusterResources()
    cluster.nodes = [make_node(f"n{i}", cpu_m=4000, mem_mib=8192)
                     for i in range(n_nodes)]
    app = ClusterResources()
    app.pods = [make_pod(f"p{i}", cpu=cpu, mem="256Mi") for i in range(n_pods)]
    return cluster, [AppResource(name="a", resources=app)]


def _snapshot(n_pods=12, pod_cpu="1500m", max_new=12):
    cluster, apps = _cluster(1, n_pods, cpu=pod_cpu)
    pods = build_pod_sequence(cluster, apps)
    template = make_node("template", cpu_m=4000, mem_mib=8192)
    return encode_cluster(
        [make_valid_node(n) for n in cluster.nodes], pods,
        EncodeOptions(max_new_nodes=max_new, new_node_template=template))


# ---- bucketing policy ---------------------------------------------------

def test_bucket_dim_pow2_then_linear_tail():
    assert [bucket_dim(n, 16, 16) for n in (1, 2, 3, 5, 9, 16)] == \
        [1, 2, 4, 8, 16, 16]
    # linear tail: multiples of the step beyond the pow2 region
    assert bucket_dim(17, 16, 16) == 32
    assert bucket_dim(33, 16, 16) == 48
    assert bucket_dim(48, 16, 16) == 48
    assert bucket_dim(0, 16, 16) == 0


def test_bucket_shape_keeps_northstar_exact():
    # the tracked bench shape must sit ON a boundary (no pad, comparable
    # series) under the default policy
    assert bucket_shape(5120, 51200) == (5120, 51200)


def test_bucket_policy_disable():
    p = BucketPolicy(enabled=False)
    assert bucket_shape(13, 37, p) == (13, 37)


def test_axis_declarations_cover_every_field():
    """Adding a SnapshotArrays field must classify its axis exactly once
    (padding or sharding a misdeclared field corrupts results silently)."""
    all_fields = {f.name for f in dataclasses.fields(SnapshotArrays)}
    declared = NODE_AXIS_FIRST | NODE_AXIS_SECOND | POD_AXIS_FIRST
    assert declared <= all_fields
    for a, b in [(NODE_AXIS_FIRST, NODE_AXIS_SECOND),
                 (NODE_AXIS_FIRST, POD_AXIS_FIRST),
                 (NODE_AXIS_SECOND, POD_AXIS_FIRST)]:
        assert not (a & b)
    # the scan's xs leaves ARE the pod axis (minus the synthesized index)
    snap = _snapshot(n_pods=2, max_new=0)
    xs_names = set(_pod_xs(snap.arrays)) - {"_pod_index"}
    assert xs_names == POD_AXIS_FIRST
    # undeclared fields are the vocab-axis arrays — pin the roster so a
    # new node/pod-axis field cannot hide there
    assert all_fields - declared == {
        "spec_alloc", "term_key", "pref_term_key", "pv_cand", "svol_key"}


def test_pad_snapshot_arrays_shapes_and_sentinels():
    snap = _snapshot(n_pods=10, max_new=2)
    a = snap.arrays
    n, p = a.alloc.shape[0], a.req.shape[0]
    padded = pad_snapshot_arrays(a, n + 5, p + 3)
    assert padded.alloc.shape[0] == n + 5
    assert padded.topo_onehot.shape[1] == n + 5
    assert padded.req.shape[0] == p + 3
    # padded nodes can never activate or host anything
    assert not padded.active[n:].any()
    assert padded.unschedulable[n:].all()
    # padded pods are bind-nothing sentinels with empty slot rows
    assert (padded.forced_node[p:] == -4).all()
    assert (padded.req[p:] == 0).all()
    assert (padded.match_gid[p:] == -1).all()
    # vocab arrays untouched
    np.testing.assert_array_equal(padded.term_key, a.term_key)


def test_pad_vector():
    v = np.array([1, 2, 3], dtype=np.int32)
    out = pad_vector(v, 5, -1)
    np.testing.assert_array_equal(out, [1, 2, 3, -1, -1])
    assert pad_vector(None, 5, -1) is None
    assert pad_vector(v, 3, -1) is v


def test_bucketed_simulate_matches_unbucketed(monkeypatch):
    """Bucketing is a pure compile-amortization move: placements, reasons
    and gpu picks must be bit-identical with the padding off."""
    cluster, apps = _cluster(5, 11)
    res_pad = simulate(cluster, apps)
    monkeypatch.setattr(exec_cache, "DEFAULT_POLICY", BucketPolicy(enabled=False))
    cluster2, apps2 = _cluster(5, 11)
    res_raw = simulate(cluster2, apps2)
    assert res_pad.placements() == res_raw.placements()
    assert [u.reason for u in res_pad.unscheduled_pods] == \
        [u.reason for u in res_raw.unscheduled_pods]
    np.testing.assert_array_equal(res_pad.fail_counts, res_raw.fail_counts)
    assert res_pad.n_active_nodes == res_raw.n_active_nodes == 5


def test_same_bucket_simulate_zero_recompiles():
    """ISSUE 4 acceptance: two consecutive simulate() calls on snapshots
    in the same bucket perform zero recompiles, observed through the
    jit-cache hit/miss counters."""
    miss = lambda: _counter("simon_compile_cache_total",  # noqa: E731
                            fn="schedule_pods", event="miss")
    hit = lambda: _counter("simon_compile_cache_total",  # noqa: E731
                           fn="schedule_pods", event="hit")

    cluster_a, apps_a = _cluster(5, 10)
    simulate(cluster_a, apps_a)          # may or may not compile (suite order)
    m0, h0 = miss(), hit()
    # one node and two pods bigger — same [8, 16] bucket
    cluster_b, apps_b = _cluster(6, 12)
    res = simulate(cluster_b, apps_b)
    assert len(res.scheduled_pods) == 12
    assert miss() == m0, "same-bucket simulate() recompiled the scan"
    assert hit() == h0 + 1


# ---- AOT executable LRU -------------------------------------------------

def test_executable_cache_lru_hit_miss_eviction():
    ev = lambda e: _counter("simon_compile_cache_total",  # noqa: E731
                            fn="lru-test", event=e)
    base = {e: ev(e) for e in ("hit", "miss", "eviction")}
    cache = ExecutableCache(capacity=2)
    built = []

    def make(tag):
        def build():
            built.append(tag)
            return tag
        return build

    assert cache.get_or_compile(("a",), "lru-test", make("a")) == "a"
    assert cache.get_or_compile(("a",), "lru-test", make("a2")) == "a"  # hit
    assert cache.get_or_compile(("b",), "lru-test", make("b")) == "b"
    assert cache.get_or_compile(("c",), "lru-test", make("c")) == "c"  # evicts a
    assert built == ["a", "b", "c"]
    assert len(cache) == 2
    assert cache.get_or_compile(("a",), "lru-test", make("a3")) == "a3"  # rebuilt
    assert ev("hit") - base["hit"] == 1
    assert ev("miss") - base["miss"] == 4
    assert ev("eviction") - base["eviction"] == 2


def test_batched_exec_cache_reuse_and_donation():
    snap = _snapshot(n_pods=8, max_new=3)
    cfg = make_config(snap)
    arrs, _, n_pods = exec_cache.bucketed_device_arrays(snap.arrays)
    lane_masks = np.zeros((2, arrs.alloc.shape[0]), dtype=bool)
    lane_masks[:, :snap.n_nodes] = active_masks_for_counts(snap, [0, 3])

    miss = lambda: _counter("simon_compile_cache_total",  # noqa: E731
                            fn="batched_schedule", event="miss")
    m0 = miss()
    out1 = run_batched_cached(arrs, lane_masks, cfg)
    m1 = miss()
    nodes1 = np.asarray(out1.node)
    # round 2 donates round 1's carry; results identical, zero new compiles
    out2 = run_batched_cached(arrs, lane_masks, cfg, carry=out1.state)
    assert miss() == m1
    np.testing.assert_array_equal(np.asarray(out2.node), nodes1)
    assert m1 - m0 <= 1  # at most one compile for this shape in the suite
    # the donated carry is dead — reading it must fail loudly
    with pytest.raises(Exception, match="deleted|donated"):
        np.asarray(out1.state.headroom)


def test_mesh_exec_cache_reuse_and_donation():
    """ISSUE 19: two same-bucket MESH launches compile exactly once
    (`simon_compile_cache_total{fn=mesh_schedule}` miss delta == 1), the
    donated-carry round is bit-identical to a fresh round (the §9 x*0
    reset, now sharded), and [S, K] traced weight lanes run under the
    mesh — digest-identical to constant mode."""
    import jax

    from open_simulator_tpu.engine.exec_cache import run_mesh_cached
    from open_simulator_tpu.engine.scheduler import weight_vector
    from open_simulator_tpu.parallel.sweep import make_mesh

    assert len(jax.devices()) >= 2  # conftest forces 8 virtual devices
    mesh = make_mesh(n_scenario=2, n_node=1, devices=jax.devices()[:2])
    snap = _snapshot(n_pods=8, max_new=3)
    cfg = make_config(snap)
    arrs, _, _ = exec_cache.bucketed_device_arrays(snap.arrays)
    lane_masks = np.zeros((2, arrs.alloc.shape[0]), dtype=bool)
    lane_masks[:, :snap.n_nodes] = active_masks_for_counts(snap, [0, 3])

    miss = lambda: _counter("simon_compile_cache_total",  # noqa: E731
                            fn="mesh_schedule", event="miss")
    m0 = miss()
    out1 = run_mesh_cached(arrs, lane_masks, cfg, mesh)
    assert miss() - m0 == 1
    nodes1 = np.asarray(out1.node)
    # same bucket -> pure cache hit, zero recompiles
    out2 = run_mesh_cached(arrs, lane_masks, cfg, mesh)
    assert miss() - m0 == 1
    np.testing.assert_array_equal(np.asarray(out2.node), nodes1)
    # round 3 donates round 2's sharded state; identical results, still
    # the one executable
    out3 = run_mesh_cached(arrs, lane_masks, cfg, mesh, carry=out2.state)
    assert miss() - m0 == 1
    np.testing.assert_array_equal(np.asarray(out3.node), nodes1)
    # the donated carry is dead — reading it must fail loudly
    with pytest.raises(Exception, match="deleted|donated"):
        np.asarray(out2.state.headroom)

    # [S, K] traced weight lanes under the mesh: every lane at the
    # config's own vector must reproduce the constant-mode digest
    cfg_t = cfg._replace(traced_weights=True)
    w = np.tile(weight_vector(cfg_t), (2, 1))
    out_w = run_mesh_cached(arrs, lane_masks, cfg_t, mesh, weights=w)
    np.testing.assert_array_equal(np.asarray(out_w.node), nodes1)


def test_persistent_cache_writes_executables(tmp_path):
    """--compile-cache-dir must actually persist compiles: jax freezes its
    on-disk cache as "disabled" on the first (import-time) compile, so
    enable_persistent_cache has to reset that state or restarts stay
    cold. A fresh-shaped simulate after enabling must write entries."""
    exec_cache.enable_persistent_cache(str(tmp_path))
    try:
        cluster, apps = _cluster(3, 7)
        # a weight no other test uses -> unique jit signature, so an
        # earlier in-memory cache hit cannot mask the persistent write
        simulate(cluster, apps, config_overrides={"w_least": 0.875})
        names = os.listdir(tmp_path)
        assert any("schedule_pods" in n for n in names), names[:5]
    finally:
        # restore: later tests must not inherit the tmp dir (they go back
        # to the suite-wide cache conftest configures, if any)
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_COMPILATION_CACHE_DIR"))
        exec_cache._persistent_dir = None


# ---- bisection sweep ----------------------------------------------------

def test_bisect_matches_exhaustive_and_dispatches_fewer_trials():
    """ISSUE 4 acceptance: capacity_bisect returns the exhaustive sweep's
    best_count while dispatching fewer device executions (observed via
    simon_sweep_trials_total)."""
    trials = lambda: _counter("simon_sweep_trials_total",  # noqa: E731
                              outcome="ok")
    snap = _snapshot(n_pods=12, pod_cpu="1500m", max_new=12)
    cfg = make_config(snap)
    t0 = trials()
    plan_ex = capacity_sweep(snap, cfg, counts=list(range(13)))
    t1 = trials()
    plan_bi = capacity_bisect(snap, cfg, max_new=12, lanes=4)
    t2 = trials()
    assert plan_ex.best_count == plan_bi.best_count == 5
    assert t1 - t0 == 13
    assert t2 - t1 < t1 - t0, (t2 - t1, t1 - t0)
    # the probed lanes agree with the exhaustive lanes where they overlap
    for i, c in enumerate(plan_bi.counts):
        assert plan_bi.satisfied[i] == plan_ex.satisfied[plan_ex.counts.index(c)]


def test_bisect_respects_thresholds():
    from open_simulator_tpu.parallel.sweep import SweepThresholds

    snap = _snapshot(n_pods=12, pod_cpu="1500m", max_new=12)
    cfg = make_config(snap)
    th = SweepThresholds(max_cpu_pct=60.0)
    plan_ex = capacity_sweep(snap, cfg, counts=list(range(13)), thresholds=th)
    plan_bi = capacity_bisect(snap, cfg, max_new=12, lanes=4, thresholds=th)
    assert plan_ex.best_count == plan_bi.best_count == 7


def test_bisect_endpoints():
    # impossible: max_new probed in round one -> one-round None verdict
    snap = _snapshot(n_pods=12, pod_cpu="1500m", max_new=2)
    cfg = make_config(snap)
    plan = capacity_bisect(snap, cfg, max_new=2, lanes=4)
    assert plan.best_count is None
    assert max(plan.counts) == 2
    # fits already: count 0 probed in round one -> one-round 0 verdict
    snap2 = _snapshot(n_pods=2, pod_cpu="100m", max_new=12)
    cfg2 = make_config(snap2)
    plan2 = capacity_bisect(snap2, cfg2, max_new=12, lanes=4)
    assert plan2.best_count == 0


def test_bisect_plan_decodes_through_applier_path():
    """The applier indexes plan.counts / nodes_per_scenario — the bisect
    plan must satisfy the same contract over its probed counts."""
    from open_simulator_tpu.core import decode_result

    snap = _snapshot(n_pods=12, pod_cpu="1500m", max_new=12)
    cfg = make_config(snap)
    plan = capacity_bisect(snap, cfg, max_new=12, lanes=4)
    idx = plan.counts.index(plan.best_count)
    masks = active_masks_for_counts(snap, plan.counts)
    result = decode_result(snap, plan.nodes_per_scenario[idx],
                           plan.fail_counts[idx], masks[idx])
    assert len(result.unscheduled_pods) == 0
    assert len(result.scheduled_pods) == snap.n_pods
