"""InterPodAffinity preferred scoring: existing-pods direction.

An already-placed pod with preferredDuringScheduling pod-affinity toward
label app=web should pull later web pods onto (or near) its node, even
though the web pods themselves declare no affinity — the direction the
vendored scoring computes from existing pods' terms.
"""

from open_simulator_tpu.core import AppResource, simulate
from open_simulator_tpu.k8s.loader import ClusterResources
from tests.conftest import make_node, make_pod


def test_existing_pod_preferred_affinity_attracts():
    magnet_aff = {"podAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [{
        "weight": 100,
        "podAffinityTerm": {
            "labelSelector": {"matchLabels": {"app": "web"}},
            "topologyKey": "kubernetes.io/hostname",
        },
    }]}}
    nodes = [make_node(f"n{i}", cpu_m=32000, mem_mib=65536) for i in range(4)]
    magnet = make_pod("magnet", cpu="100m", labels={"app": "magnet"}, affinity=magnet_aff,
                      node_name="n2")
    web = make_pod("web-0", cpu="100m", labels={"app": "web"})
    cluster = ClusterResources()
    cluster.nodes = nodes
    cluster.pods = [magnet]
    app = ClusterResources()
    app.pods = [web]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert res.placements()["default/web-0"] == "n2"


def test_existing_pod_preferred_anti_affinity_repels():
    repel_aff = {"podAntiAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [{
        "weight": 100,
        "podAffinityTerm": {
            "labelSelector": {"matchLabels": {"app": "web"}},
            "topologyKey": "kubernetes.io/hostname",
        },
    }]}}
    nodes = [make_node("n0", cpu_m=32000), make_node("n1", cpu_m=32000)]
    repeller = make_pod("repeller", cpu="100m", labels={"app": "x"}, affinity=repel_aff,
                        node_name="n0")
    web = make_pod("web-0", cpu="100m", labels={"app": "web"})
    cluster = ClusterResources()
    cluster.nodes = nodes
    cluster.pods = [repeller]
    app = ClusterResources()
    app.pods = [web]
    res = simulate(cluster, [AppResource(name="a", resources=app)])
    assert res.placements()["default/web-0"] == "n1"
