"""Scratch A/B timing harness (not part of the package): best-of-N reps,
prints one number. Usage: python .perf_ab.py [preset] [reps]"""
import sys, time, json
import jax, jax.numpy as jnp
import __graft_entry__ as ge
from open_simulator_tpu.engine.scheduler import device_arrays, make_config, schedule_pods
from open_simulator_tpu.parallel.sweep import active_masks_for_counts

preset = sys.argv[1] if len(sys.argv) > 1 else "default"
reps = int(sys.argv[2]) if len(sys.argv) > 2 else 15
shapes = {
    "default": (1024, 2048, 256, 64),
    "northstar": (5120, 51200, 64, 64),
    "ns-small": (5120, 8192, 64, 64),
}
n, p, s, max_new = shapes[preset]
snap = ge._synthetic_snapshot(n_nodes=n, n_pods=p, max_new=max_new)
cfg = make_config(snap)._replace(fail_reasons=False)
arrs = device_arrays(snap)
counts = [min(i % (max_new + 1), max_new) for i in range(s)]
masks = jnp.asarray(active_masks_for_counts(snap, counts))
fn = jax.jit(jax.vmap(lambda a: schedule_pods(arrs, a, cfg)))
out = fn(masks); jax.block_until_ready(out.node)
best = 1e9
ts = []
for _ in range(reps):
    t0 = time.perf_counter(); out = fn(masks); jax.block_until_ready(out.node)
    dt = time.perf_counter() - t0
    ts.append(dt); best = min(best, dt)
print(json.dumps({"preset": preset, "best_ms": round(best*1e3, 2),
                  "pods_per_s": round(p*s/best/1e6, 3),
                  "scen_per_s": round(s/best, 1),
                  "med_ms": round(sorted(ts)[len(ts)//2]*1e3, 2)}))
