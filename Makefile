# Developer entry points. `make smoke` is the documented pre-PR check:
# graftlint + the tier-1 verify command from ROADMAP.md plus one chaos
# scenario end to end (tools/smoke.sh).

.PHONY: test lint smoke bench

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

# static trace-safety / engine-contract analysis (rules GL1-GL5);
# exits nonzero on any finding — see ARCHITECTURE.md "graftlint"
lint:
	python -m open_simulator_tpu.cli lint

smoke:
	bash tools/smoke.sh

bench:
	python bench.py
