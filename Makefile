# Developer entry points. `make smoke` is the documented pre-PR check:
# graftlint + the tier-1 verify command from ROADMAP.md plus one chaos
# scenario end to end (tools/smoke.sh).

.PHONY: test lint smoke bench bench-smoke bench-regress lifecycle-smoke \
	multichip-smoke campaign-smoke replay-smoke session-smoke serve-smoke \
	tune-smoke fault-smoke journal-smoke trace-smoke live-smoke

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

# static trace-safety / engine-contract analysis (rules GL1-GL5);
# exits nonzero on any finding — see ARCHITECTURE.md "graftlint".
# Full tree, all rules (GL0-GL10), parallel parse; `simon-tpu lint
# --changed` is the fast pre-commit subset, this target stays strict.
lint:
	python -m open_simulator_tpu.cli lint --jobs 4

smoke:
	bash tools/smoke.sh

bench:
	python bench.py

# the driver's bench contract at toy scale: the demo preset must emit one
# parseable JSON line with value > 0 (BENCH_r01-r05 recorded a TypeError
# for five rounds because nothing ran bench.py outside the judge)
bench-smoke:
	env JAX_PLATFORMS=cpu python bench.py --preset demo --skip-baseline \
	  | python -c "import json,sys; \
lines=[l for l in sys.stdin if l.strip().startswith('{')]; \
d=json.loads(lines[-1]); \
assert d['value'] > 0, d; \
print('bench-smoke OK:', d['metric'], d['value'], d['unit'])"

# graceful-drain smoke against a real server process: SIGTERM with one
# request in flight must flip /readyz (not /healthz), reject new work
# with 503, finish the held request, and write the final ledger record
lifecycle-smoke:
	env JAX_PLATFORMS=cpu python tools/lifecycle_smoke.py

# the 8-device gate (ROADMAP item 1): batched_schedule over a
# (scenario x node) mesh of 8 virtual CPU devices must produce
# BIT-IDENTICAL node assignments (ledger digest equality) to the
# single-device run — incl. the wave-scheduled pools workload. The
# MULTICHIP_r01-r05 rot (five rounds of a silently recorded crash)
# cannot recur while this is in smoke.
multichip-smoke:
	env XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  JAX_PLATFORMS=cpu python tools/multichip_smoke.py

# fleet fault-isolation gate: a 3-cluster fixture fleet (one malformed)
# must complete with exactly 1 quarantined cluster, audits passing on
# the good ones; a child process SIGKILLed after cluster 1 must resume
# via the campaign journal to a BIT-IDENTICAL fleet report digest, with
# the quarantined cluster reported once (not re-run, not lost)
campaign-smoke:
	env JAX_PLATFORMS=cpu python tools/campaign_smoke.py

# time-axis gate (replay/): a synthetic arrival trace with one mid-trace
# kill_node must converge under the autoscaler; a child SIGKILLed after
# step 3 must resume via the replay journal to a BIT-IDENTICAL trajectory
# digest; and the frontier CLI must return a non-trivial Pareto set
replay-smoke:
	env JAX_PLATFORMS=cpu python tools/replay_smoke.py

# digital-twin gate (replay/session.py): a journaled session on a real
# server survives SIGKILL — the restarted server serves it with a
# BIT-IDENTICAL trajectory digest (also vs an uninterrupted reference
# run) — and a chaos fork completes / a poisoned fork quarantines while
# the mainline keeps settling events
session-smoke:
	env JAX_PLATFORMS=cpu python tools/session_smoke.py

# inference-serving gate (server/serving.py): POST a cluster once, probe
# it by digest — delta probes must digest bit-identically to cold full
# re-encodes; mixed coalesced/singleton load with ONE poisoned lane must
# answer the siblings 200 with singleton digests (the poisoned member
# gets its own 504); SIGTERM drain finishes the in-flight probe, exits 0
serve-smoke:
	env JAX_PLATFORMS=cpu python tools/serve_smoke.py

# policy-search gate (tune/): a real server must answer a grid round's
# Pareto set over (unplaced, cost, disruption), reproduce a seeded cem
# digest, turn a lapsed deadline into a structured 504 and a bogus
# weight into a 400, and run a 6-cluster 2-bucket fleet campaign in 2
# launches (the fleet-lane witness: launches < clusters)
tune-smoke:
	env JAX_PLATFORMS=cpu python tools/tune_smoke.py

# device-fault-domain gate (resilience/faults.py): a real server under
# an injected SIMON_FAULT_PLAN must answer the poisoned launch with a
# structured 5xx (taxonomy code, never a bare traceback) while siblings
# answer 200; the OOM pair walks the cache_drop -> resident_drop ladder
# and still returns the healthy digest; simon_fault_* counters match
# the plan exactly; SIGTERM under the plan still exits 0
fault-smoke:
	env JAX_PLATFORMS=cpu python tools/fault_smoke.py

# durable-state fault-domain gate (resilience/journal.py): SIGKILL a
# real server mid-session, then damage the journals both ways — a torn
# FINAL line must resume digest-identically while a flipped byte
# mid-file answers a structured 409 E_CORRUPT (kind/record/offset, the
# sibling unharmed); an injected ENOSPC plan walks the shared
# checkpointing_disabled rung with simon_journal_* counters matching;
# SIGTERM under the plan still exits 0
journal-smoke:
	env JAX_PLATFORMS=cpu python tools/journal_smoke.py

# causal-tracing gate (telemetry/context.py): a real server must echo a
# client X-Simon-Trace-Id and reconstruct the request's causal timeline
# (queue wait, coalesced launch, durable journal appends) from the black
# box; /debug/executables lists harvested XLA costs; a deterministic
# OOM plan yields a structured 503 whose timeline records the ladder
# rungs and attempts plus a trace:dump ledger event; SIGTERM under
# traced load still exits 0
trace-smoke:
	env JAX_PLATFORMS=cpu python tools/trace_smoke.py

# live-operations gate (telemetry/live.py): a real server must stream
# the black box over GET /api/events (an SSE follower sees the same
# causal sequence /api/trace/<id> reconstructs), drop a stalled
# subscriber's events without blocking any worker, expose the per-owner
# device-memory ledger on /debug/stats + /metrics, render one
# `simon-tpu top --once` frame, and end live streams cleanly on SIGTERM
live-smoke:
	env JAX_PLATFORMS=cpu python tools/live_smoke.py

# regression gate over the run ledger (SIMON_LEDGER_DIR or
# BENCH_LEDGER_DIR=... make bench-regress): the newest bench record per
# shape must stay within the threshold of the trailing median of its
# priors; exits 0 with a notice when the ledger holds < 2 bench records
bench-regress:
	python tools/bench_regress.py --ledger-dir "$${BENCH_LEDGER_DIR:-$${SIMON_LEDGER_DIR:-}}"
