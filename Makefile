# Developer entry points. `make smoke` is the documented pre-PR check:
# the tier-1 verify command from ROADMAP.md plus one chaos scenario
# end to end (tools/smoke.sh).

.PHONY: test smoke bench

test:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

smoke:
	bash tools/smoke.sh

bench:
	python bench.py
