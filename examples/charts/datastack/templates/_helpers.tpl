{{- define "datastack.labels" -}}
app.kubernetes.io/instance: {{ .Release.Name }}
team: {{ .Values.global.team | quote }}
{{- end -}}
